"""Persistent warm-worker pool: amortize spawn/init across sweep rows.

Before this module, ``isolation='subprocess'`` paid a full child-process
lifecycle for EVERY row: Python interpreter start, JAX import, PJRT
client init, mesh build — seconds of fixed setup per row on the CPU sim
and much more against a remote TPU relay, dwarfing the measurement
itself on cartesian sweeps (ISSUE 5; the same amortize-the-fixed-cost
argument T3 and HiCCL make for collective launch overhead). The pool
replaces spawn-per-row with **one long-lived child per environment
signature**: the parent leases a worker, streams row configs to it over
a request queue, and reuses it across every row whose environment is
compatible — keeping the JAX runtime, the PJRT client, the process's
jit caches and the persistent compile cache warm between rows.

Design points, each load-bearing:

- **Environment signature** (``pool_signature``): the env vars that are
  baked into a child at spawn and cannot change afterwards — the
  simulated world (``DDLB_TPU_SIM_DEVICES``/``_SLICES``), the
  distributed topology, process-level XLA flags (``XLA_FLAGS`` is read
  once at backend creation — primitives/xla_options.py), the compile
  cache, trace dir and fault plan. A lease under a different signature
  retires the old worker and spawns a fresh one. Per-executable
  ``compiler_options`` (the xla_options sweep axis) deliberately do NOT
  key the signature: jit-level options need no new process.
- **Per-row isolation contract preserved**: the dispatch loop clears
  the child's in-memory jit caches at executable-signature boundaries
  (``config_signature``) — exactly the granularity the in-process
  runner uses — so same-signature neighbors share a warm cache and
  different ones cannot leak state. The persistent disk cache is
  untouched by design. Operators who suspect cross-row leakage anyway
  can force spawn-per-row back with ``pool_max_rows=1`` (the degenerate
  case this pool keeps byte-compatible).
- **Fault machinery composes** (ISSUE 4): the heartbeat deadline is
  per ROW (silence measured from dispatch, ``max(start, last_beat)``),
  a hung/SIGKILLed worker is killed and marked dead so the next lease
  respawns, and the killed worker's row is retried by the runner on
  that fresh lease; lifecycle faults announce queue markers before
  executing so attribution survives child death. Quarantine and retry
  policy stay in the runner, unaffected.
- **Compile-ahead targets the leased worker** (PR 1): each row request
  may carry the NEXT row's config; the child prefetch-compiles it on a
  background thread while the current row's timing loop owns the
  device, landing executables in the persistent cache the same process
  reads back one row later (utils/compile_ahead.make_worker_scheduler).
- The parent side is deliberately JAX-free (importable from bench.py
  and the queue driver, which must never initialize a backend); all
  accelerator work happens in the child.

``scripts/lint.py`` bans direct ``ctx.Process(`` construction in the
package outside this file, so future row execution cannot silently
regress to cold spawns.
"""

from __future__ import annotations

import importlib
import os
import sys
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

from ddlb_tpu import envs, faults, telemetry
from ddlb_tpu.faults import flightrec, heartbeat
from ddlb_tpu.observatory import live

#: env vars that are baked into a worker at spawn time; a change in any
#: of them makes a live worker unusable for the next row (see module
#: docstring for why per-jit compiler_options are deliberately absent)
SIGNATURE_ENV_KEYS = (
    "DDLB_TPU_SIM_DEVICES",
    "DDLB_TPU_SIM_SLICES",
    "DDLB_TPU_NUM_PROCESSES",
    "DDLB_TPU_PROCESS_ID",
    "DDLB_TPU_COORD_ADDR",
    "DDLB_TPU_COMPILE_CACHE",
    "DDLB_TPU_TRACE",
    "DDLB_TPU_LIVE",
    "DDLB_TPU_FAULT_PLAN",
    "DDLB_TPU_CHIP",
    "XLA_FLAGS",
    "JAX_PLATFORMS",
    "LIBTPU_INIT_ARGS",
)


def pool_signature(extra: Optional[Dict[str, Any]] = None) -> Tuple:
    """The environment signature a worker is leased under: a snapshot of
    the spawn-time env vars (world size / sim topology, process-level
    XLA flags, compile cache, fault plan) plus caller extras."""
    items = tuple((k, os.environ.get(k, "")) for k in SIGNATURE_ENV_KEYS)
    return items + (tuple(sorted((extra or {}).items())),)


class AwaitResult(NamedTuple):
    """Outcome of waiting on a worker's response queue for one request.

    ``row`` is the posted result (a row dict, or a ``run_call`` return
    value) — None when the worker died, hung past the deadline, or the
    call errored, in which case ``error`` says why. ``markers`` are the
    fault sites the child announced before executing them (attribution
    for faults that killed it). ``worker_dead`` means the lease must
    respawn before the next row. ``partial`` is the last intermediate
    result the child posted (``post_partial``) — the salvage channel for
    a worker that produced a headline and then hung in a sidecar."""

    row: Optional[Any]
    error: str
    markers: List[str]
    worker_dead: bool
    partial: Optional[Any] = None


def _release_queue(queue: Any) -> None:
    """Close an mp.Queue whose reader/writer may be a killed process:
    close + cancel_join_thread so the parent's interpreter exit can
    never block on the feeder thread of a dead child's queue."""
    try:
        queue.close()
        queue.cancel_join_thread()
    except (OSError, ValueError, AttributeError):
        pass  # already released, or a test fake without the surface


def _classify_message(msg, markers: List[str], message_sink):
    """Sort one response-queue message: ('consumed', None) for markers /
    ready lines, ('partial', v), ('call_error', str), or
    ('terminal', payload) for a row or call result."""
    if isinstance(msg, dict):
        if "__fault_marker__" in msg:
            markers.append(str(msg["__fault_marker__"]))
            return "consumed", None
        if "__pool_ready__" in msg:
            if message_sink is not None:
                message_sink(msg)
            return "consumed", None
        if "__pool_partial__" in msg:
            return "partial", msg["__pool_partial__"]
        if "__pool_call_error__" in msg:
            return "call_error", str(msg["__pool_call_error__"])
        if "__pool_call_result__" in msg:
            return "terminal", msg["__pool_call_result__"]
    return "terminal", msg


def await_row(
    proc,
    queue,
    heartbeat_channel,
    worker_timeout: Optional[float] = None,
    message_sink: Optional[Callable[[Dict[str, Any]], None]] = None,
    join_grace: float = 10.0,
    hard_timeout: Optional[float] = None,
) -> AwaitResult:
    """The hung/dead-child policy for one dispatched request (the former
    runner ``_await_worker_row``, factored here so every consumer — the
    sweep runner, the hardware queue, bench — shares ONE policy and
    tests can drive it with scripted children). Polls in short slices: a
    child that DIES without posting a result (segfault, OOM-kill) is
    reported immediately; one that goes SILENT — no result, no heartbeat
    — for ``worker_timeout`` seconds is killed (the heartbeat deadline
    is per row: silence is measured from THIS dispatch, and a beating
    child extends its own deadline; faults/heartbeat.py).
    ``hard_timeout`` additionally caps total WALL time for the request,
    beats or no beats — the hardware queue's old per-attempt budget,
    which a beating-but-unbounded row must not escape. Monotonic clocks
    throughout, immune to NTP steps mid-capture."""
    import queue as queue_mod

    start = time.monotonic()
    markers: List[str] = []
    partial = None
    while True:
        # wall cap checked every iteration, not just on queue-Empty: a
        # child streaming partials/markers faster than once per second
        # must not escape the budget
        if (
            hard_timeout
            and time.monotonic() - start > hard_timeout
            and proc.is_alive()
        ):
            proc.kill()
            proc.join(join_grace)
            _release_queue(queue)
            live.post_event(
                "worker_dead", worker=getattr(proc, "pid", None),
                error=f"wall cap {hard_timeout:.0f}s exceeded (killed)",
            )
            return AwaitResult(
                None,
                f"TimeoutError: worker exceeded {hard_timeout:.0f}s"
                f" (killed)",
                markers,
                True,
                partial,
            )
        try:
            msg = queue.get(timeout=1.0)
        except queue_mod.Empty:
            if not proc.is_alive():
                # died; drain in case the result (or a fired-fault
                # marker) raced the exit
                try:
                    while True:
                        msg = queue.get(timeout=1.0)
                        kind, payload = _classify_message(
                            msg, markers, message_sink
                        )
                        if kind == "terminal":
                            return AwaitResult(
                                payload, "", markers, False, partial
                            )
                        if kind == "call_error":
                            return AwaitResult(
                                None, payload, markers, False, partial
                            )
                        if kind == "partial":
                            partial = payload
                except queue_mod.Empty:
                    live.post_event(
                        "worker_dead", worker=getattr(proc, "pid", None),
                        error=f"exit code {proc.exitcode} with no result",
                    )
                    return AwaitResult(
                        None,
                        f"WorkerDied: exit code {proc.exitcode} "
                        f"with no result",
                        markers,
                        True,
                        partial,
                    )
            # the dashboard's per-worker liveness line: the heartbeat
            # age exactly as the kill policy below sees it (env-gated
            # no-op by default; one line per 1 s poll slice when on)
            last_sign = max(
                start,
                heartbeat.last_beat(heartbeat_channel)
                if heartbeat_channel is not None
                else 0.0,
            )
            live.post_event(
                "worker_beat", worker=getattr(proc, "pid", None),
                age_s=round(time.monotonic() - last_sign, 1),
            )
            if worker_timeout:
                if time.monotonic() - last_sign > worker_timeout:
                    proc.kill()
                    proc.join(join_grace)
                    # a killed child's queue feeder thread may hold
                    # buffered data; release it so the parent's
                    # interpreter exit can never block on it
                    _release_queue(queue)
                    beat = (
                        heartbeat_channel is not None
                        and heartbeat.last_beat(heartbeat_channel) > 0
                    )
                    live.post_event(
                        "worker_dead", worker=getattr(proc, "pid", None),
                        error=f"silent for {worker_timeout}s (killed)",
                    )
                    return AwaitResult(
                        None,
                        f"TimeoutError: worker silent for "
                        f"{worker_timeout}s "
                        f"{'since last heartbeat' if beat else 'with no heartbeat'}"
                        f" (killed)",
                        markers,
                        True,
                        partial,
                    )
            continue
        kind, payload = _classify_message(msg, markers, message_sink)
        if kind == "terminal":
            return AwaitResult(payload, "", markers, False, partial)
        if kind == "call_error":
            return AwaitResult(None, payload, markers, False, partial)
        if kind == "partial":
            partial = payload


def merge_fault_markers(row, markers: List[str]):
    """Fold announced-fired fault sites into the row's
    ``fault_injected`` column (markers first, deduplicated) — the
    attribution channel for faults that killed the child before it
    could post a row."""
    if markers and isinstance(row, dict):
        fired = [
            s for s in str(row.get("fault_injected") or "").split(",") if s
        ]
        row["fault_injected"] = ",".join(dict.fromkeys(markers + fired))
    return row


def run_one_row(
    pool: "WorkerPool",
    config: Dict[str, Any],
    error_row_fn: Callable[[Dict[str, Any], str], Dict[str, Any]],
    prefetch: Optional[Dict[str, Any]] = None,
    hard_timeout: Optional[float] = None,
) -> Dict[str, Any]:
    """Lease → dispatch → attribute: the ONE row-execution path every
    pool consumer shares (the sweep runner and the hardware queue's
    ``PooledRunner``), so reuse/setup attribution, fault-marker merging
    and the invalidate-on-transient policy cannot drift between them.
    ``error_row_fn(config, error)`` builds the dead/hung-worker row."""
    from ddlb_tpu.faults.classify import TRANSIENT, classify_error

    worker = pool.lease(pool_signature())
    reused = worker.rows_run > 0
    # the flight recorder's pool-row entry: in a launched world the
    # parent's sequence shows which row was in flight when a rank
    # wedged, next to the child's own phase marks in the same rank file
    flightrec.mark(
        "pool.row", impl=config.get("impl_id"),
        worker=getattr(worker.proc, "pid", None), reused=reused,
    )
    outcome = worker.run_row(
        config, prefetch=prefetch, hard_timeout=hard_timeout
    )
    if outcome.row is None:
        row = error_row_fn(config, outcome.error)
    else:
        row = outcome.row
    row = merge_fault_markers(row, outcome.markers)
    if isinstance(row, dict):
        # the pool's amortization, visible per row (on error rows too):
        # did this row reuse a warm process, and what did its setup cost
        # when it did not
        row["worker_reused"] = bool(reused)
        setup = 0.0 if reused else worker.setup_s
        # NaN (worker died before reporting) passes through unrounded
        row["worker_setup_s"] = round(setup, 4) if setup == setup else setup
        error = str(row.get("error") or "")
        if error and classify_error(
            error, bool(row.get("valid", True))
        ) == TRANSIENT:
            # a transient failure (RESOURCE_EXHAUSTED, timeout kill,
            # worker death) may have wedged the child's backend: retire
            # the lease so the retry runs on a fresh one
            pool.invalidate()
    return row


# ---------------------------------------------------------------------------
# Child side: the dispatch loop
# ---------------------------------------------------------------------------

#: set while a ``run_call`` target executes in the child: posts
#: intermediate results back to the parent (see ``post_partial``)
_partial_sink: Optional[Callable[[Any], None]] = None


def post_partial(value: Any) -> None:
    """From inside a ``run_call`` target: post an intermediate result to
    the leasing parent. If the target later hangs or dies, the parent's
    ``AwaitResult.partial`` still carries the last posted value (bench
    uses this so a wedged int8 sidecar cannot erase a measured
    headline). No-op outside a pool worker."""
    sink = _partial_sink
    if sink is not None:
        sink(value)


def _run_call(req: Dict[str, Any], response_queue) -> None:
    """Execute a ``{"kind": "call"}`` request: import ``module:function``
    and post its return value (or the exception) back."""
    global _partial_sink
    target = str(req.get("target", ""))
    module_name, _, fn_name = target.partition(":")
    _partial_sink = lambda v: response_queue.put({"__pool_partial__": v})
    try:
        fn = getattr(importlib.import_module(module_name), fn_name)
        result = fn(**(req.get("kwargs") or {}))
    except Exception as exc:
        response_queue.put(
            {"__pool_call_error__": f"{type(exc).__name__}: {exc}"}
        )
        return
    finally:
        _partial_sink = None
    response_queue.put({"__pool_call_result__": result})


def _pool_child_main(
    request_queue, response_queue, heartbeat_channel, quiet: bool = False
):  # pragma: no cover - child process
    """Worker child entry: initialize the runtime ONCE, then loop on the
    request queue running one benchmark row (or call) per request until
    the shutdown sentinel (``None``). Hosts the same per-row fault
    surface the old spawn-per-row child did — ``subprocess.entry``
    (hang / abrupt exit / OOM-style SIGKILL) and ``subprocess.result``
    (corrupted numerics), each announced to the parent as a queue marker
    BEFORE executing so a fault that kills this process stays
    attributable (the brief sleep lets the queue's feeder thread flush
    the marker ahead of an abrupt ``os._exit``/SIGKILL)."""
    if quiet:
        # the leasing parent's stdout is a one-line artifact (bench):
        # route the child's prints/diagnostics to stderr instead
        sys.stdout = sys.stderr
    heartbeat.set_channel(heartbeat_channel)
    t0 = time.monotonic()
    from ddlb_tpu.runtime import Runtime, configure_compile_cache

    configure_compile_cache()
    runtime = Runtime()
    heartbeat.beat()
    ready = {"__pool_ready__": True, "setup_s": time.monotonic() - t0}
    ready.update(runtime.info())
    response_queue.put(ready)

    from ddlb_tpu.benchmark import benchmark_worker
    from ddlb_tpu.utils.compile_ahead import (
        config_signature,
        make_worker_scheduler,
    )

    def _announce(site: str, kind: str) -> None:
        response_queue.put({"__fault_marker__": site, "kind": kind})
        if kind in ("exit", "kill", "hang"):
            time.sleep(0.25)

    scheduler = None
    scheduler_init = False
    prev_sig = None
    while True:
        req = request_queue.get()
        if not isinstance(req, dict):  # None = shutdown sentinel
            break
        heartbeat.beat()  # per-row deadline starts counting from receipt
        if req.get("kind") == "call":
            _run_call(req, response_queue)
            continue
        config = req.get("config") or {}
        if not scheduler_init:
            # lazily, once: None without a persistent compile cache
            # (same rule as the in-process runner — without the disk
            # cache a prefetched executable has no channel to the next
            # row's fresh jit closures)
            scheduler = make_worker_scheduler()
            scheduler_init = True
        scheduler_busy = False
        if scheduler is not None:
            # reap the previous row's prefetch before touching caches —
            # never clear under an active compile thread
            scheduler.wait(timeout=scheduler.WAIT_TIMEOUT_S)
            scheduler_busy = scheduler.busy
        sig = config_signature(config)
        if prev_sig is not None and sig != prev_sig and not scheduler_busy:
            # the cross-row isolation contract, at the same granularity
            # as the in-process runner: clear the in-memory jit caches
            # at executable-signature boundaries (the persistent disk
            # cache is untouched by design)
            import jax

            jax.clear_caches()
        prev_sig = sig
        if scheduler is not None and req.get("prefetch"):
            # compile-ahead in the leased worker: the NEXT row's
            # executables compile on a background thread while this
            # row's timing loop owns the device, landing in the
            # persistent cache THIS process reads back one row later
            scheduler.prefetch(req["prefetch"])
        # per-site fault counters restart at zero for every row — the
        # plan's determinism contract assumes one row == one fresh
        # process, and a reused worker must inject exactly what a
        # spawn-per-row child would (faults.plan.reset_counts)
        faults.reset_counts()
        faults.set_fire_listener(_announce)
        try:
            with faults.scope(
                attempt=int(config.get("fault_attempt", 0) or 0),
                impl=config.get("impl_id"),
                primitive=config.get("primitive"),
            ):
                faults.inject("subprocess.entry")
                row = benchmark_worker(config)
                row = faults.corrupt_row("subprocess.result", row)
        finally:
            faults.set_fire_listener(None)
        response_queue.put(row)
    if scheduler is not None:
        scheduler.shutdown()


# ---------------------------------------------------------------------------
# Parent side: leases
# ---------------------------------------------------------------------------


class PoolWorker:
    """One leased child process: its queues, heartbeat channel, row
    budget and liveness. Constructed by ``WorkerPool._spawn`` only."""

    def __init__(
        self,
        signature: Tuple,
        proc,
        request_queue,
        response_queue,
        heartbeat_channel,
        worker_timeout: Optional[float] = None,
        max_rows: int = 0,
    ) -> None:
        self.signature = signature
        self.proc = proc
        self.request_queue = request_queue
        self.response_queue = response_queue
        self.heartbeat_channel = heartbeat_channel
        self.worker_timeout = worker_timeout
        self.max_rows = int(max_rows or 0)
        #: rows dispatched to this worker (not necessarily completed)
        self.rows_run = 0
        #: the child's self-reported init cost (JAX import + PJRT client
        #: + device list), from its ready message; NaN until ready
        self.setup_s = float("nan")
        self.ready_info: Optional[Dict[str, Any]] = None
        self._dead = False
        self._retired = False

    def alive(self) -> bool:
        return not self._dead and self.proc.is_alive()

    def _on_message(self, msg: Dict[str, Any]) -> None:
        """Consume a ``__pool_ready__`` line whenever the await loop (or
        ``wait_ready``) encounters one."""
        self.setup_s = float(msg.get("setup_s", float("nan")))
        self.ready_info = dict(msg)
        live.post_event(
            "worker_ready", worker=getattr(self.proc, "pid", None),
            setup_s=self.setup_s, platform=msg.get("platform"),
        )

    def wait_ready(self, timeout: float = 120.0) -> Optional[Dict[str, Any]]:
        """Block until the child posts its ready message (platform,
        device count, setup_s) — the pool's backend probe. Returns the
        info dict, or None if the child died or the timeout passed."""
        import queue as queue_mod

        if self.ready_info is not None:
            return self.ready_info
        deadline = time.monotonic() + timeout
        markers: List[str] = []
        while time.monotonic() < deadline:
            try:
                msg = self.response_queue.get(timeout=1.0)
            except queue_mod.Empty:
                if not self.proc.is_alive():
                    self._dead = True
                    return None
                continue
            _classify_message(msg, markers, self._on_message)
            if self.ready_info is not None:
                return self.ready_info
        return None

    def run_row(
        self,
        config: Dict[str, Any],
        prefetch: Optional[Dict[str, Any]] = None,
        hard_timeout: Optional[float] = None,
    ) -> AwaitResult:
        """Dispatch one benchmark row config; block for its result under
        the per-row heartbeat deadline (plus the optional
        ``hard_timeout`` wall cap). ``prefetch`` is the NEXT row's
        config for the child's compile-ahead thread."""
        self.rows_run += 1
        req: Dict[str, Any] = {"kind": "row", "config": dict(config)}
        if prefetch:
            req["prefetch"] = dict(prefetch)
        self.request_queue.put(req)
        result = await_row(
            self.proc,
            self.response_queue,
            self.heartbeat_channel,
            self.worker_timeout,
            message_sink=self._on_message,
            hard_timeout=hard_timeout,
        )
        if result.worker_dead:
            self._dead = True
            self._retired = True  # killed/exited: nothing left to retire
        elif self.max_rows > 0 and self.rows_run >= self.max_rows:
            # row budget spent: retire NOW so the chip/devices free
            # before the next lease spawns (pool_max_rows=1 thereby
            # behaves exactly like the old spawn-per-row path)
            self.retire()
        return result

    def run_call(
        self,
        target: str,
        kwargs: Optional[Dict[str, Any]] = None,
        timeout: Optional[float] = None,
    ) -> AwaitResult:
        """Dispatch a ``module:function`` call (bench's headline path);
        ``timeout`` overrides the worker's row deadline for this call."""
        self.rows_run += 1
        self.request_queue.put(
            {"kind": "call", "target": target, "kwargs": dict(kwargs or {})}
        )
        result = await_row(
            self.proc,
            self.response_queue,
            self.heartbeat_channel,
            self.worker_timeout if timeout is None else timeout,
            message_sink=self._on_message,
        )
        if result.worker_dead:
            self._dead = True
            self._retired = True
        return result

    def retire(
        self, timeout: Optional[float] = None, graceful: bool = True
    ) -> None:
        """Shut the child down and release the queues. Idempotent.

        Graceful (healthy worker): shutdown sentinel, bounded join
        (capped at 60 s — teardown of an idle child is quick; a longer
        ``worker_timeout`` must not stretch a planned recycle), kill if
        it hangs in teardown (runtime/atexit finalizers). Non-graceful
        (a worker being invalidated as hung/wedged): kill immediately —
        a sentinel would sit unread behind whatever wedged it, and the
        join would burn the caller's whole timeout budget."""
        if self._retired:
            return
        self._retired = True
        self._dead = True
        try:
            if graceful and self.proc.is_alive():
                self.request_queue.put(None)
                self.proc.join(min(timeout or 60.0, 60.0))
            if self.proc.is_alive():
                self.proc.kill()
                self.proc.join(10.0)
        finally:
            _release_queue(self.response_queue)
            _release_queue(self.request_queue)


class WorkerPool:
    """Lease manager: at most ONE live worker at a time (a TPU child
    locks the chip for its process lifetime, so a second live worker
    could never initialize), keyed by environment signature and recycled
    after ``max_rows`` rows (0 = unlimited; 1 = the spawn-per-row
    degenerate case). ``lease`` reuses the live worker when the
    signature matches and the row budget allows, and otherwise retires
    it and spawns fresh — emitting ``pool.lease`` / ``pool.reuse`` /
    ``pool.respawn`` telemetry so a trace shows exactly where spawn cost
    was paid."""

    def __init__(
        self,
        max_rows: Optional[int] = None,
        worker_timeout: Optional[float] = None,
        quiet_child: bool = False,
    ) -> None:
        self.max_rows = (
            envs.get_pool_max_rows() if max_rows is None else int(max_rows)
        )
        self.worker_timeout = worker_timeout
        self.quiet_child = quiet_child
        self._worker: Optional[PoolWorker] = None
        #: lifetime counters for the sweep log / tests
        self.spawns = 0
        self.reuses = 0
        self.respawns = 0

    def lease(self, signature: Tuple) -> PoolWorker:
        """A worker compatible with ``signature``: the live one when it
        matches (and has row budget left), else a fresh spawn."""
        worker = self._worker
        with telemetry.span("pool.lease", cat="pool"):
            if (
                worker is not None
                and worker.alive()
                and worker.signature == signature
                and (self.max_rows <= 0 or worker.rows_run < self.max_rows)
            ):
                self.reuses += 1
                telemetry.record("pool.reuses")
                telemetry.instant(
                    "pool.reuse", cat="pool", rows_run=worker.rows_run
                )
                return worker
            respawn = worker is not None
            # budget-exhausted workers self-retire right after their
            # last row (chip release), so check the row budget BEFORE
            # liveness or a planned recycle would masquerade as "dead"
            reason = (
                "first"
                if worker is None
                else "signature"
                if worker.signature != signature
                else "recycled"
                if self.max_rows > 0 and worker.rows_run >= self.max_rows
                else "dead"
                if not worker.alive()
                else "recycled"
            )
            if worker is not None:
                worker.retire(timeout=self.worker_timeout)
                self._worker = None
            with telemetry.span(
                "pool.respawn" if respawn else "pool.spawn",
                cat="pool",
                reason=reason,
            ):
                self._worker = self._spawn(signature)
            live.post_event(
                "worker_spawn",
                worker=getattr(
                    getattr(self._worker, "proc", None), "pid", None
                ),
                reason=reason,
            )
            self.spawns += 1
            telemetry.record("pool.spawns")
            if respawn:
                self.respawns += 1
                telemetry.record("pool.respawns")
            return self._worker

    def _spawn(self, signature: Tuple) -> PoolWorker:
        """Start one worker child (spawn context: forked JAX state is
        unusable). The ONLY Process construction site for row execution
        in the package — scripts/lint.py enforces it."""
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        request_queue = ctx.Queue()
        response_queue = ctx.Queue()
        channel = heartbeat.new_channel(ctx)
        proc = ctx.Process(
            target=_pool_child_main,
            args=(request_queue, response_queue, channel, self.quiet_child),
            # daemon: a crashed parent can never orphan a chip-holding
            # child (daemons are terminated at parent exit)
            daemon=True,
        )
        proc.start()
        return PoolWorker(
            signature,
            proc,
            request_queue,
            response_queue,
            channel,
            worker_timeout=self.worker_timeout,
            max_rows=self.max_rows,
        )

    def invalidate(self) -> None:
        """Retire the live worker so the next lease spawns fresh — the
        caller's remedy after a row whose transient failure (e.g.
        RESOURCE_EXHAUSTED) may have wedged the child's backend. The
        suspect worker is killed outright (non-graceful): a wedged
        child would never read a shutdown sentinel, and a bounded join
        on it would stall the capture window for nothing."""
        worker, self._worker = self._worker, None
        if worker is not None:
            telemetry.record("pool.invalidations")
            worker.retire(timeout=self.worker_timeout, graceful=False)

    def shutdown(self) -> None:
        """Gracefully retire whatever is live (the healthy-end-of-sweep
        path: the child gets to flush trace shards and reap its
        compile-ahead thread); idempotent, bounded."""
        worker, self._worker = self._worker, None
        if worker is not None:
            worker.retire(timeout=self.worker_timeout)
