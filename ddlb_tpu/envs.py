"""Environment-variable accessors for distributed bootstrap.

TPU-native analogue of the reference's env layer
(/root/reference/ddlb/envs.py:12-82): the same fallback-chain pattern
(explicit DDLB var -> launcher-provided vars -> default), retargeted at the
launchers a TPU pod actually sees (GKE/Cloud TPU, SLURM, MPI/PMI) plus a
CPU-simulation knob the reference lacks (SURVEY.md section 7 step 1).

All accessors read ``os.environ`` lazily so tests can monkeypatch them.
"""

from __future__ import annotations

import os
from typing import Callable, Sequence, TypeVar

T = TypeVar("T")

# Explicit framework overrides always win; then launcher fallback chains.
_PROCESS_ID_VARS = (
    "DDLB_TPU_PROCESS_ID",
    "CLOUD_TPU_TASK_ID",
    "TPU_WORKER_ID",
    "OMPI_COMM_WORLD_RANK",
    "SLURM_PROCID",
    "PMI_RANK",
)
_NUM_PROCESSES_VARS = (
    "DDLB_TPU_NUM_PROCESSES",
    "OMPI_COMM_WORLD_SIZE",
    "SLURM_NTASKS",
    "PMI_SIZE",
)
_LOCAL_PROCESS_ID_VARS = (
    "DDLB_TPU_LOCAL_PROCESS_ID",
    "OMPI_COMM_WORLD_LOCAL_RANK",
    "SLURM_LOCALID",
)


def get_env(
    names: Sequence[str],
    default: T,
    cast: Callable[[str], T] = str,  # type: ignore[assignment]
) -> T:
    """Return the first set env var among ``names`` cast via ``cast``.

    Mirrors the fallback-chain idiom of the reference's ``get_env``
    (/root/reference/ddlb/envs.py:12-47).
    """
    for name in names:
        value = os.environ.get(name)
        if value is not None and value != "":
            return cast(value)
    return default


def get_process_id() -> int:
    """Global process index (reference ``get_rank``, envs.py:50-55)."""
    return get_env(_PROCESS_ID_VARS, 0, int)


def get_num_processes() -> int:
    """Global process count (reference ``get_world_size``, envs.py:58-62)."""
    return get_env(_NUM_PROCESSES_VARS, 1, int)


def get_local_process_id() -> int:
    """Per-host process index (reference ``get_local_rank``, envs.py:56-57)."""
    return get_env(_LOCAL_PROCESS_ID_VARS, 0, int)


def get_coordinator_address() -> str:
    """``jax.distributed`` coordinator ``host:port``.

    Reference analogue: ``get_jax_coord_addr`` (envs.py:76-82) plus the
    DDLB_MASTER_ADDR/PORT pair (envs.py:64-74) collapsed into one address,
    since JAX needs a single coordinator endpoint rather than a TCP-store
    rendezvous.
    """
    addr = os.environ.get("DDLB_TPU_COORD_ADDR") or os.environ.get("JAX_COORD_ADDR")
    if addr:
        return addr
    host = os.environ.get("DDLB_TPU_MASTER_ADDR", "127.0.0.1")
    port = os.environ.get("DDLB_TPU_MASTER_PORT", "12355")
    return f"{host}:{port}"


def get_sim_device_count() -> int:
    """Number of simulated host devices (0 = disabled; no reference analogue).

    When positive, the runtime forces the CPU platform with this many virtual
    devices so multi-chip sharding is testable on one host — the functional
    addition SURVEY.md section 4 calls out as the reference's biggest gap.
    """
    return get_env(("DDLB_TPU_SIM_DEVICES",), 0, int)


def get_compile_cache_dir() -> str:
    """Persistent XLA compilation-cache directory ("" = disabled).

    When set, the runtime points ``jax_compilation_cache_dir`` here so
    repeated or resumed sweeps reuse compiled executables across
    processes (and across ``jax.clear_caches()``) instead of re-paying
    cold compiles — the compile-ahead engine's cross-process banking
    layer (utils/compile_ahead.py). Follows the DDLB_TPU_* convention:
    empty/unset disables.
    """
    return os.environ.get("DDLB_TPU_COMPILE_CACHE", "").strip()


def get_trace_dir() -> str:
    """Structured-trace output directory ("" = tracing disabled).

    When set, ``ddlb_tpu.telemetry`` spans are written as Chrome
    ``trace_event`` JSON lines to a per-process shard under this
    directory (``trace-<host>-p<rank>-<pid>.jsonl``); the sweep runner
    (or ``scripts/trace_report.py``) merges shards into a
    Perfetto/``chrome://tracing``-loadable ``trace.json``. Follows the
    DDLB_TPU_* convention: empty/unset disables.
    """
    return os.environ.get("DDLB_TPU_TRACE", "").strip()


def get_fault_plan() -> str:
    """Fault-injection plan ("" = injection disabled).

    Inline JSON or a path to a JSON file describing seeded fault rules
    (``ddlb_tpu.faults.plan``). When set, the named injection sites
    threaded through the stack (compile, worker phases, collective
    entry, subprocess lifecycle) consult the plan; unset keeps the
    zero-overhead fast path. Follows the DDLB_TPU_* convention:
    empty/unset disables.
    """
    return os.environ.get("DDLB_TPU_FAULT_PLAN", "").strip()


def get_max_retries() -> int:
    """Default per-row retry budget for transient failures (default 2).

    The self-healing sweep runner retries a row classified transient
    (``ddlb_tpu.faults.classify``) up to this many times with
    exponential backoff + jitter before recording the error row. 0
    disables retries; an explicit runner argument overrides.
    """
    return get_env(("DDLB_TPU_MAX_RETRIES",), 2, int)


def get_quarantine_after() -> int:
    """Consecutive failed rows before an implementation is quarantined
    (default 3; 0 disables quarantine).

    Once an implementation's configs fail this many times in a row
    (after their retry budgets), the runner stops spawning workers for
    its remaining configs and emits cheap ``skipped: quarantined`` rows
    instead — graceful degradation in place of N timeouts.
    """
    return get_env(("DDLB_TPU_QUARANTINE_AFTER",), 3, int)


def get_worker_pool() -> bool:
    """Whether subprocess-isolation rows run on the persistent warm-
    worker pool (default on; ``DDLB_TPU_WORKER_POOL=0`` disables).

    On: the runner leases one long-lived child per environment
    signature and streams row configs to it (``ddlb_tpu.pool``),
    amortizing process spawn, JAX import, PJRT init and mesh build
    across the sweep. Off: every row pays a fresh spawn — equivalent to
    ``pool_max_rows=1``, kept for suspected cross-row state leakage.
    """
    return get_env(("DDLB_TPU_WORKER_POOL",), 1, int) != 0


def get_pool_max_rows() -> int:
    """Rows a pool worker may run before being recycled (default 0 =
    unlimited; ``DDLB_TPU_POOL_MAX_ROWS``).

    1 is the spawn-per-row degenerate case (one fresh process per row,
    byte-identical CSV schema); small values bound cross-row state
    accumulation (jit-cache growth, allocator high-water creep) on long
    hardware sweeps.
    """
    return get_env(("DDLB_TPU_POOL_MAX_ROWS",), 0, int)


def get_history_dir() -> str:
    """Run-history bank directory ("" = banking disabled).

    When set, every runner path (sweep runner, pooled hardware queue,
    bench headline) appends its result rows to
    ``<dir>/history.jsonl`` — the perf observatory's cross-run store
    (``ddlb_tpu.observatory.store``), keyed by chip + family + impl +
    config signature + git rev. ``scripts/observatory_report.py``
    compares runs against it. Follows the DDLB_TPU_* convention:
    empty/unset disables.
    """
    return os.environ.get("DDLB_TPU_HISTORY", "").strip()


def get_calib_path() -> str:
    """Calibration-table JSON path ("" = uncalibrated).

    When set, the prediction stack loads the versioned calibration table
    (``ddlb_tpu.perfmodel.calib``) fitted from banked observatory
    history: ``cost.calibrated_estimate`` prices per-hop latency /
    per-step software overhead / per-row dispatch constants on top of
    the bandwidth lower bound, the simulator's replay adds the same
    terms per step, and every runner row is stamped with
    ``predicted_cal_s`` / ``cal_residual_frac`` / ``cal_version``.
    Unset keeps every prediction the raw analytical bound and the three
    columns at their defaults — byte-identical rows. Follows the
    DDLB_TPU_* convention: empty/unset disables.
    """
    return os.environ.get("DDLB_TPU_CALIB", "").strip()


def get_tuning_table_path() -> str:
    """Tuning-table JSON path ("" = untuned defaults).

    When set, member construction consults the versioned per-chip
    tuning table (``ddlb_tpu.tuner.table``) banked by the prior-guided
    search driver (``ddlb_tpu.tuner.driver``): a table hit applies the
    banked winning knobs (Pallas tiles, ``chunk_count``, composition)
    in place of the registered defaults — explicit per-config options
    always win — and stamps the row's ``tuned`` / ``tuning_version`` /
    ``prior_rank`` columns. Unset keeps every member on its registered
    defaults and the three columns inert — byte-identical rows.
    Follows the DDLB_TPU_* convention: empty/unset disables.
    """
    return os.environ.get("DDLB_TPU_TUNING", "").strip()


def get_live_path() -> str:
    """Live sweep-stream file ("" = stream disabled).

    When set, the runner, the worker pool and the hardware queue append
    one JSON event line per row dispatch/phase/completion and worker
    lifecycle change (``ddlb_tpu.observatory.live``);
    ``scripts/sweep_dash.py`` tails it to render the live dashboard.
    Strictly append-only observation: the measured path never reads it.
    Follows the DDLB_TPU_* convention: empty/unset disables.
    """
    return os.environ.get("DDLB_TPU_LIVE", "").strip()


def get_chip_override() -> str:
    """Chip-spec name override ("" = auto-detect from PJRT).

    When set, ``perfmodel.specs.detect_spec`` and the HBM budget gate
    resolve this name in the hardware registry instead of querying the
    backend — unknown names raise there (a silently-wrong roofline
    denominator is worse than a crash). Follows the DDLB_TPU_*
    convention: empty/unset auto-detects.
    """
    return os.environ.get("DDLB_TPU_CHIP", "").strip()


def get_topology_override() -> str:
    """Simulator topology selection ("" = the consumer's default).

    The one sanctioned read of ``DDLB_TPU_TOPOLOGY``: a spec string
    (``<chip>:<pods>x<ici_dim>[x...]``, e.g. ``v5p:4x16x16``) or a
    preset name resolved by ``perfmodel.topology.resolve_topology``.
    ``scripts/sim_report.py`` and the demo read their default world from
    here; the benchmark CLI's ``--topology`` flag exports it so one
    launcher invocation pins the world for every downstream consumer.
    Follows the DDLB_TPU_* convention: empty/unset defers.
    """
    return os.environ.get("DDLB_TPU_TOPOLOGY", "").strip()


def get_autotune_cache_path() -> str:
    """Autotune-cache JSON path override ("" = the repo-root default).

    ``utils.autotune`` persists tuned Pallas block sizes here, keyed by
    (kernel, shape, dtype, device kind); tests point it at a tmp file.
    """
    return os.environ.get("DDLB_TPU_AUTOTUNE_CACHE", "").strip()


def get_run_id_override() -> str:
    """Observatory run-id override ("" = generate per process).

    Multi-process captures that must bank under one history id set
    this; otherwise ``observatory.store`` stamps a timestamp+pid id
    once per driver process.
    """
    return os.environ.get("DDLB_TPU_RUN_ID", "").strip()


def get_world_size_override() -> str:
    """Device-count override for subprocess-isolation resume keys
    ("" = probe; returned raw because the runner warns on a non-integer
    value rather than silently dropping it).

    On flaky hardware the 120 s world-size probe is pure cost when the
    operator already knows the topology; "0" keeps the DDLB_TPU_*
    convention (disabled).
    """
    return os.environ.get("DDLB_TPU_WORLD_SIZE", "").strip()


def get_no_native() -> bool:
    """Whether the native host-runtime library is force-disabled
    (``DDLB_TPU_NO_NATIVE=1``; used by tests to cover the pure-Python
    fallbacks)."""
    return bool(os.environ.get("DDLB_TPU_NO_NATIVE"))


def get_flightrec_dir() -> str:
    """Collective flight-recorder run directory ("" = recording off).

    When set, every process appends sequenced progress entries
    (collective entries/exits, mesh builds, worker phase marks, pool
    rows — ``ddlb_tpu.faults.flightrec``) to a per-rank
    ``flight-p<rank>.jsonl`` under this shared directory, crash-safely
    (one flushed line per transition, so even a SIGKILLed rank leaves
    its completed sequence on disk). ``scripts/flight_report.py`` joins
    the per-rank files to name the lagging rank and the divergence
    site after a wedged or killed world. The supervised launcher
    (``cli/launch.py --supervise``) sets it for every child. Follows
    the DDLB_TPU_* convention: empty/unset disables.
    """
    return os.environ.get("DDLB_TPU_FLIGHTREC", "").strip()


def get_beat_file() -> str:
    """File-based progress-beat path ("" = file beats off).

    When set, ``faults.heartbeat.beat()`` additionally publishes the
    process's last-beat ``time.monotonic()`` stamp to this file
    (atomic tmp+rename, throttled) — the cross-PROCESS form of the
    shared-memory beat channel, readable by a supervisor that did not
    fork the process (``cli/launch.py --supervise`` points each rank
    at ``<run_dir>/beat-p<rank>``). CLOCK_MONOTONIC is system-wide on
    the hosts the fleet runs, so the supervisor compares the stamp
    against its own monotonic clock. Follows the DDLB_TPU_*
    convention: empty/unset disables.
    """
    return os.environ.get("DDLB_TPU_BEAT_FILE", "").strip()


def get_physical_rank() -> int:
    """This process's PHYSICAL world slot (default: the process id).

    The supervised launcher's degraded relaunch (``cli/launch.py``)
    shrinks the world around an indicted slot: the surviving ranks get
    fresh contiguous process ids (jax.distributed needs 0..N-1) but
    keep their original slot number here, so topology-scoped fault
    rules (``faults.plan`` ``topo``/``ranks`` selectors) keep targeting
    the same *hardware* — a relaunch that excluded the bad slot
    genuinely dodges the fault instead of re-rolling it onto whoever
    inherited process id 1.
    """
    return get_env(("DDLB_TPU_PHYS_RANK",), get_process_id(), int)


def get_physical_world() -> int:
    """The FULL physical world size (default: the process count).

    Topology fault rules compute ring neighbors modulo the physical
    ring (``faults.plan.FaultRule.affected_rank``); on a degraded
    relaunch the process count SHRINKS while physical slot ids keep
    full-world numbering, so the launcher exports the original size
    here — otherwise an ``rx``-direction link fault would wrap around
    the shrunken count and re-target a surviving healthy slot.
    """
    return get_env(("DDLB_TPU_PHYS_WORLD",), get_num_processes(), int)


def get_world_degraded() -> bool:
    """Whether this world is a DEGRADED relaunch (shrunk/remapped
    around an indicted rank) — stamped onto every result row as the
    ``world_degraded`` column so banked history can tell a full-world
    measurement from a limp-mode one. Set by the supervised launcher;
    empty/unset = a full healthy world.
    """
    return bool(os.environ.get("DDLB_TPU_WORLD_DEGRADED", "").strip())


def get_world_attempt() -> int:
    """Which world-level launch attempt this process belongs to
    (default 0 = the first launch).

    The supervised launcher exports the relaunch attempt number to
    every child; fault-plan rules treat it as a floor on the retry
    attempt (``fail_attempts`` gating), so a seeded rank-targeted
    fault with ``fail_attempts: 1`` fires on the first world launch
    and clears on the supervised relaunch — the world-level
    transient-recovery shape.
    """
    return get_env(("DDLB_TPU_WORLD_ATTEMPT",), 0, int)


def get_sim_slice_count() -> int:
    """Simulated TPU slice count for the DCN topology axis (0 = off).

    Partitions the (virtual) device list into N equal contiguous "slices"
    so the ici/dcn transport dimension — the TPU analogue of the
    reference's collective-backend axis (nccl/ucc/tl-*, SURVEY.md section
    2.4) — is exercisable without multi-slice hardware.
    """
    return get_env(("DDLB_TPU_SIM_SLICES",), 0, int)
