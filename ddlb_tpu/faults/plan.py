"""Seeded fault plan: named injection sites, deterministic firing.

A plan is JSON — inline in ``DDLB_TPU_FAULT_PLAN`` or a path to a file —
of the form::

    {"seed": 0, "rules": [
      {"site": "subprocess.entry", "kind": "hang",
       "match": {"impl": "jax_spmd_0"}, "fail_attempts": 1},
      {"site": "worker.warmup", "kind": "transient_error",
       "match": {"impl": "overlap_0"}},
      {"site": "worker.validate", "kind": "corrupt",
       "match": {"impl": "xla_gspmd"}, "fail_attempts": 99}
    ]}

Rule fields (all optional except ``site`` and ``kind``):

- ``site``: injection-site name, matched with ``fnmatch`` so
  ``"worker.*"`` covers every worker phase;
- ``kind``: one of ``hang`` (sleep ``duration_s``, default 3600 — the
  parent's ``worker_timeout`` is what kills it), ``exit`` (abrupt
  ``os._exit(exit_code)``, no row posted), ``kill`` (SIGKILL to self,
  the OOM-killer signature), ``transient_error`` (raises
  ``TimeoutError`` — the retryable class), ``deterministic_error``
  (raises ``ValueError`` — parks immediately), ``corrupt`` (consumed by
  ``corrupt``/``corrupt_row`` at result-carrying sites; ``inject``
  ignores it), or one of the **topology fault kinds** below;
- ``match``: substring filters on the active scope's context, e.g.
  ``{"impl": "overlap"}`` / ``{"primitive": "tp_"}``;
- ``ranks``: list of process ids the rule applies to (default: every
  rank). A multi-process chaos plan is shared by the whole world
  (``DDLB_TPU_FAULT_PLAN`` is inherited), so ``"ranks": [1]`` is what
  lets one seeded plan kill/hang exactly rank 1 mid-collective while
  its peers run clean — the rank-targeted battery of
  ``scripts/chaos_launch.py``. Matching uses the PHYSICAL rank
  (``DDLB_TPU_PHYS_RANK``, exported by the supervised launcher's
  degraded relaunch; falls back to the process id) so a world
  relaunched WITHOUT an indicted slot genuinely dodges the rule that
  targeted it;

**Topology fault kinds** (ISSUE 15): at multi-pod scale the dominant
failure is not a crash but a *degraded* component — one slow ICI link
or throttled chip dragging every collective. The kinds ``link_slow``,
``link_down`` and ``chip_slow`` model exactly that, selected by a
``topo`` dict instead of rank globs::

    {"site": "runtime.*", "kind": "link_slow",
     "topo": {"axis": "ici", "index": 1, "direction": "tx",
              "factor": 0.25},
     "sim_link_gbs": 1e-6}

- ``topo.axis``: the link class (``ici`` / ``dcn`` — CPU-sim realizes
  both on the process ring);
- ``topo.index``: which link (``index`` connects rank ``index`` to
  rank ``index+1`` on the ring) or, for ``chip_slow``, which chip;
- ``topo.direction``: ``tx`` (the sender, rank ``index``, is delayed)
  or ``rx`` (the receiver, rank ``index+1 mod world``) — realized
  identically in CPU-sim, carried so the health verdict can name the
  directed link;
- ``topo.factor``: the surviving bandwidth fraction in ``(0, 1]`` —
  ``0.25`` is "this link runs at quarter rate";
- ``sim_link_gbs``: the *simulated* healthy link rate in GB/s the
  CPU-sim realization prices the delay against (default: the cpu-sim
  chip spec's class rate, which makes the delay negligible — a chaos
  plan that wants a measurable CPU-sim skew declares a small rate,
  since the host never actually moves bytes at ICI speeds).

Realization at the registered collective sites (``runtime.barrier``,
``runtime.collective``, the ``overlap.ring_step`` schedule walk):
``link_slow`` / ``chip_slow`` sleep the deterministic
payload-proportional extra time a factor-degraded link costs —
``perfmodel.cost.link_slow_extra_s(payload, bw, factor)``, the SAME
closed form the simulator's ``Degradation`` overlay prices, so a
seeded "ICI link at 0.25x" produces the skew signature the clock-sync
fold (ISSUE 14) measures AND the degraded-world replay predicts.
``link_down`` raises a ``link_down`` transport error on the affected
rank, which ``faults.classify`` classes DEGRADED (the mitigating
relaunch's trigger), never transient.
- ``probability``: firing probability per eligible call (default 1.0),
  decided by a **deterministic stream** seeded from
  ``(plan seed, site, call index)`` — same seed, same injections, in
  any process;
- ``at``: explicit 0-based per-site call indices to fire on (overrides
  ``probability``);
- ``until``: fire only while the per-site call count is BELOW this —
  the fault-that-clears-mid-run shape (ISSUE 19): a ``hang`` rule on
  ``serve.decode_tick`` with ``until: 400`` inflates TPOT for the
  first 400 ticks and then goes quiet, which is what lets a chaos
  drill exercise probation/exoneration (the indicted shard's probes
  run fast once the fault exhausts). Composes with ``at``/
  ``probability`` (the ``until`` gate applies first);
- ``fail_attempts``: fire only while the row's retry attempt (from the
  active ``scope``) is below this (default 1: the first attempt faults,
  the retry runs clean — the transient-recovery shape). Set it high to
  model a deterministic, never-recovering fault. The supervised
  launcher's world relaunch counter (``DDLB_TPU_WORLD_ATTEMPT``) acts
  as a floor on the attempt, so a world-killing fault with the default
  gate fires on the first launch and clears on the relaunch — the
  world-level transient-recovery shape;
- ``duration_s`` / ``exit_code``: kind parameters.

Determinism contract: firing depends only on (plan seed, site name,
per-site call index within the process, rule match, attempt). A retried
subprocess worker is a fresh process whose site counters restart at
zero, so ``fail_attempts`` — not counter state — is what lets a
transient fault clear on the retry.
"""

from __future__ import annotations

import fnmatch
import json
import os
import random
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from contextlib import contextmanager

from ddlb_tpu import envs, telemetry

#: The registry of injection sites that actually exist in the code —
#: one entry per ``faults.inject``/``corrupt``/``corrupt_row`` call site
#: threaded through the stack. A plan rule whose ``site`` glob matches
#: none of these would silently never fire (the seeded chaos battery
#: would "pass" without injecting anything), so the static analyzer
#: (DDLB104, ``ddlb_tpu/analysis``) cross-checks every site literal and
#: plan glob against this dict. Adding an injection site means adding
#: its name here — the analyzer fails otherwise.
SITES: Dict[str, str] = {
    "compile.aot": "AOT compile of one executable (utils/compile_ahead)",
    "compile.prefetch": "background compile-ahead prefetch of config N+1",
    "worker.setup": "benchmark_worker input/mesh setup phase",
    "worker.warmup": "benchmark_worker warmup iterations",
    "worker.timing": "benchmark_worker timed measurement loop",
    "worker.validate": "benchmark_worker result validation phase",
    "worker.result": "result-array corruption before validation",
    "runtime.mesh": "Runtime mesh construction",
    "runtime.barrier": "Runtime cross-process barrier",
    "runtime.collective": "cross-process result collective (timing MAX-reduce)",
    "launch.child": "launched-world child bootstrap (Runtime init, pre-connect)",
    "skew.fold": (
        "cross-rank skew fold's stamp allgather (telemetry/clocksync) — "
        "a rank-targeted fault here models a rank dying/wedging inside "
        "the observability collective itself"
    ),
    "overlap.ring_step": (
        "chunked-fusion ring-schedule walk (ops/chunked_fusion"
        ".plan_report) — the host-side per-hop planning step where a "
        "topology fault (link_slow/chip_slow) charges its payload-"
        "proportional delay on the affected rank, surfacing as that "
        "rank's late arrival at the next collective"
    ),
    "subprocess.entry": "pool child dispatch-loop row entry",
    "subprocess.result": "row dict corruption before posting to parent",
    "serve.admit": "serving engine request admission (prefill + slot copy)",
    "serve.decode_tick": (
        "serving engine ragged decode tick (kind=hang + duration_s = "
        "the per-token latency-injection shape the SLO gate catches); "
        "the cluster stamps context shard=<i>, so a plan can slow ONE "
        "engine of a pool (the indictment drill's seeded straggler)"
    ),
    "serve.route": (
        "serving cluster routing decision (ddlb_tpu/serve/router.py) — "
        "one call per dispatched request, context shard=<chosen>"
    ),
    "serve.handoff": (
        "prefill->decode KV-bundle handoff (ddlb_tpu/serve/cluster.py); "
        "payload_bytes carries the bundle size, so link_slow rules "
        "price a degraded interconnect against the real KV payload"
    ),
}


_UNSET = object()

_lock = threading.Lock()
_plan: Any = _UNSET  # _UNSET -> not loaded yet; None -> no plan active
_counts: Dict[str, int] = {}
_tls = threading.local()
#: optional process-wide hook called as fn(site, kind) when a rule
#: fires — the subprocess worker uses it to announce a fired lifecycle
#: fault to its parent BEFORE the fault kills the process
_fire_listener: Optional[Any] = None


def set_fire_listener(fn) -> None:
    """Install (or clear, with None) the fired-rule announcement hook."""
    global _fire_listener
    _fire_listener = fn


#: the topology-scoped fault kinds (degraded-component model, ISSUE 15)
TOPO_KINDS = ("link_slow", "link_down", "chip_slow")


class FaultRule:
    """One plan rule; see the module docstring for field semantics."""

    def __init__(self, spec: Dict[str, Any]) -> None:
        if "site" not in spec or "kind" not in spec:
            raise ValueError(f"fault rule needs 'site' and 'kind': {spec!r}")
        self.site = str(spec["site"])
        self.kind = str(spec["kind"])
        if self.kind not in (
            "hang", "exit", "kill", "transient_error",
            "deterministic_error", "corrupt", *TOPO_KINDS,
        ):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        self.match = {str(k): str(v) for k, v in spec.get("match", {}).items()}
        self.ranks = spec.get("ranks")
        if self.ranks is not None:
            self.ranks = [int(r) for r in self.ranks]
        self.probability = float(spec.get("probability", 1.0))
        self.at = spec.get("at")
        if self.at is not None:
            self.at = [int(i) for i in self.at]
        self.until = spec.get("until")
        if self.until is not None:
            self.until = int(self.until)
        self.fail_attempts = int(spec.get("fail_attempts", 1))
        self.duration_s = float(spec.get("duration_s", 3600.0))
        self.exit_code = int(spec.get("exit_code", 1))
        self.topo: Optional[Dict[str, Any]] = None
        self.sim_link_gbs = spec.get("sim_link_gbs")
        if self.kind in TOPO_KINDS:
            topo = spec.get("topo")
            if not isinstance(topo, dict) or "index" not in topo:
                raise ValueError(
                    f"topology fault kind {self.kind!r} needs a 'topo' "
                    f"dict with at least 'index': {spec!r}"
                )
            factor = float(topo.get("factor", 1.0))
            if self.kind != "link_down" and not (0.0 < factor <= 1.0):
                raise ValueError(
                    f"{self.kind} topo.factor must be in (0, 1], got "
                    f"{factor}"
                )
            direction = str(topo.get("direction", "tx"))
            if direction not in ("tx", "rx"):
                raise ValueError(
                    f"topo.direction must be 'tx' or 'rx', got "
                    f"{direction!r}"
                )
            self.topo = {
                "axis": str(topo.get("axis", "ici")),
                "index": int(topo["index"]),
                "direction": direction,
                "factor": factor,
            }

    def affected_rank(self) -> Optional[int]:
        """The PHYSICAL rank a topology-scoped rule degrades: the chip
        itself for ``chip_slow``; for link kinds, link ``index``
        connects rank ``index`` -> rank ``index+1`` on the CPU-sim
        process ring, so ``tx`` degrades rank ``index`` and ``rx`` the
        receiver ``index+1 mod world``. The modulo rides the FULL
        physical ring (``envs.get_physical_world`` — exported by the
        supervised launcher), never the possibly-shrunken process
        count: a degraded relaunch keeps full-world slot numbering,
        and wrapping around the shrunk count would re-target a
        surviving healthy slot. None for non-topo rules."""
        if self.topo is None:
            return None
        index = self.topo["index"]
        if self.kind == "chip_slow" or self.topo["direction"] == "tx":
            return index
        world = max(1, envs.get_physical_world())
        return (index + 1) % world

    def link_label(self) -> str:
        """Human name of the degraded component (the health verdict's
        link vocabulary): ``ici[1->2]`` / ``chip[1]``."""
        if self.topo is None:
            return ""
        if self.kind == "chip_slow":
            return f"chip[{self.topo['index']}]"
        world = max(1, envs.get_physical_world())
        i = self.topo["index"]
        return f"{self.topo['axis']}[{i}->{(i + 1) % world}]"

    def delay_s(self, payload_bytes: float) -> float:
        """The payload-proportional extra seconds this rule's degraded
        link charges one crossing — ``perfmodel.cost.link_slow_extra_s``
        with the rule's simulated link rate (see module docstring), the
        same closed form the simulator's ``Degradation`` overlay
        prices."""
        from ddlb_tpu.perfmodel.cost import link_slow_extra_s
        from ddlb_tpu.perfmodel.specs import get_spec

        if self.topo is None or payload_bytes <= 0.0:
            return 0.0
        if self.sim_link_gbs is not None:
            bw = float(self.sim_link_gbs) * 1e9
        else:
            spec = get_spec("cpu-sim")
            transport = "dcn" if self.topo["axis"] == "dcn" else "ici"
            bw = spec.link_bw(transport)
        return link_slow_extra_s(
            float(payload_bytes), bw, self.topo["factor"]
        )

    def matches(self, site: str, context: Dict[str, str]) -> bool:
        if not fnmatch.fnmatchcase(site, self.site):
            return False
        if self.ranks is not None and (
            envs.get_physical_rank() not in self.ranks
        ):
            return False
        affected = self.affected_rank()
        if affected is not None and envs.get_physical_rank() != affected:
            return False
        for key, needle in self.match.items():
            if needle not in context.get(key, ""):
                return False
        return True

    def fires(self, seed: int, site: str, count: int, attempt: int) -> bool:
        """Deterministic firing decision for per-site call ``count``."""
        if attempt >= self.fail_attempts:
            return False
        if self.until is not None and count >= self.until:
            return False
        if self.at is not None:
            return count in self.at
        if self.probability >= 1.0:
            return True
        # str seeds hash via SHA-512 in CPython's Random — stable across
        # processes and runs, unlike hash() (which is salted)
        rng = random.Random(f"{seed}:{site}:{count}")
        return rng.random() < self.probability


class FaultPlan:
    """A parsed plan: seed + ordered rule list (first match wins)."""

    def __init__(self, spec: Dict[str, Any]) -> None:
        self.seed = int(spec.get("seed", 0))
        self.rules: List[FaultRule] = [
            FaultRule(r) for r in spec.get("rules", [])
        ]

    def pick(
        self, site: str, count: int, context: Dict[str, str], attempt: int,
        kinds: Optional[tuple] = None,
    ) -> Optional[FaultRule]:
        """First rule that matches ``site``/``context`` and fires at this
        call index, restricted to ``kinds`` when given."""
        for rule in self.rules:
            if kinds is not None and rule.kind not in kinds:
                continue
            if rule.matches(site, context) and rule.fires(
                self.seed, site, count, attempt
            ):
                return rule
        return None


def load_plan(text: Optional[str] = None) -> Optional[FaultPlan]:
    """Parse a plan from ``text`` (inline JSON or a file path), defaulting
    to ``DDLB_TPU_FAULT_PLAN``; caches the result. Returns None (and
    keeps the zero-overhead fast path) when the knob is unset/empty. A
    malformed plan raises: a chaos run silently running fault-free would
    defeat its purpose."""
    global _plan
    with _lock:
        if text is None and _plan is not _UNSET:
            return _plan
        raw = text if text is not None else envs.get_fault_plan()
        raw = (raw or "").strip()
        if not raw:
            _plan = None
            return None
        if not raw.lstrip().startswith("{"):
            with open(raw, encoding="utf-8") as f:
                raw = f.read()
        _plan = FaultPlan(json.loads(raw))
        return _plan


def reset() -> None:
    """Drop the cached plan, per-site counters, and any fire listener
    (test helper)."""
    global _plan, _fire_listener
    with _lock:
        _plan = _UNSET
        _counts.clear()
        _fire_listener = None


def reset_counts() -> None:
    """Restart every per-site call counter at zero, keeping the loaded
    plan. The determinism contract above assumes one row == one fresh
    process; a REUSED warm-pool worker (ddlb_tpu/pool.py) runs many
    rows in one process, so its dispatch loop calls this at every row
    boundary — a seeded plan then injects identically whether a row ran
    pooled or spawn-per-row."""
    with _lock:
        _counts.clear()


def active() -> bool:
    """True when a fault plan is loaded (loading it on first call)."""
    return load_plan() is not None


# ---------------------------------------------------------------------------
# Scope: retry-attempt / impl context + fired-site collection
# ---------------------------------------------------------------------------


class _Scope:
    """One active frame: match context plus the sites that fired in it."""

    def __init__(self, context: Dict[str, str], attempt: int) -> None:
        self.context = context
        self.attempt = attempt
        self.fired: List[str] = []


def _frames() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = []
        _tls.stack = stack
    return stack


@contextmanager
def scope(
    attempt: int = 0, **context: Any
) -> Iterator[_Scope]:
    """Frame under which injection sites see this row's retry ``attempt``
    and match ``context`` (impl=..., primitive=...), and which collects
    the names of sites that fired — the row's ``fault_injected`` column.
    Nests: an inner frame shadows context, fired sites land in every
    active frame."""
    frame = _Scope(
        {k: str(v) for k, v in context.items() if v is not None},
        int(attempt),
    )
    stack = _frames()
    stack.append(frame)
    try:
        yield frame
    finally:
        stack.remove(frame)


def _active_frame() -> Optional[_Scope]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def _next_count(site: str) -> int:
    with _lock:
        count = _counts.get(site, 0)
        _counts[site] = count + 1
    return count


def _fired(site: str, rule: FaultRule) -> None:
    telemetry.record("fault.injected")
    telemetry.instant(
        "fault.inject", cat="fault", site=site, kind=rule.kind
    )
    telemetry.warn(f"fault injected: kind={rule.kind} at site={site}")
    for frame in _frames():
        frame.fired.append(site)
    listener = _fire_listener
    if listener is not None:
        try:
            listener(site, rule.kind)
        except Exception as exc:
            telemetry.warn(
                f"fault fire listener failed: {type(exc).__name__}: {exc}"
            )


# ---------------------------------------------------------------------------
# Injection entry points
# ---------------------------------------------------------------------------


def _resolve(site: str, context: Dict[str, Any], kinds: tuple, fire=True):
    """Shared slow path: the firing rule for this call of ``site`` under
    the active scope's context, or None. Callers already checked that a
    plan might be active (the ``is None`` fast path). ``fire=False``
    defers the fired-bookkeeping to the caller — for faults that may
    turn out inapplicable (corruption of an unsupported value type),
    which must never be RECORDED as injected without actually
    happening."""
    plan = _plan
    if plan is _UNSET:
        plan = load_plan()
    if plan is None:
        return None
    frame = _active_frame()
    ctx = dict(frame.context) if frame else {}
    for key, value in context.items():
        if value is not None:
            ctx[key] = str(value)
    # the world-relaunch counter floors the attempt: a fresh child of a
    # relaunched world has scope attempt 0, but its fault-plan gating
    # must see "this world already failed once" (fail_attempts)
    attempt = max(
        frame.attempt if frame else 0, envs.get_world_attempt()
    )
    rule = plan.pick(site, _next_count(site), ctx, attempt, kinds=kinds)
    if rule is not None and fire:
        _fired(site, rule)
    return rule


def inject(
    site: str, payload_bytes: float = 0.0, **context: Any
) -> None:
    """Injection site: no-op unless a loaded plan has a firing rule here,
    in which case the configured fault happens (raise / hang / abrupt
    process death / degraded-link delay). The no-plan fast path is one
    ``is None`` check. ``payload_bytes`` is what the site would move
    over the wire — the quantity the topology fault kinds price their
    payload-proportional delay against (collective sites pass their
    real payload; sites that pass nothing see zero topo delay)."""
    if _plan is None:
        return
    rule = _resolve(
        site, context,
        ("hang", "exit", "kill", "transient_error", "deterministic_error",
         *TOPO_KINDS),
    )
    if rule is None:
        return
    if rule.kind == "hang":
        time.sleep(rule.duration_s)
        return
    if rule.kind in ("link_slow", "chip_slow"):
        # the degraded-component realization: the deterministic extra
        # time a factor-degraded link costs this payload, charged as a
        # sleep on the affected rank — its peers then measure exactly
        # the arrival-skew signature the clock-sync fold attributes
        extra = rule.delay_s(payload_bytes)
        if extra > 0.0:
            telemetry.record("fault.delay_s", extra)
            time.sleep(extra)
        return
    if rule.kind == "link_down":
        raise ConnectionError(
            f"injected link_down at {site}: {rule.link_label()} is down"
        )
    if rule.kind == "exit":
        os._exit(rule.exit_code)
    if rule.kind == "kill":
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    if rule.kind == "transient_error":
        raise TimeoutError(
            f"injected transient fault at {site} (a retry should clear it)"
        )
    raise ValueError(f"injected deterministic fault at {site}")


def _corrupt_value(value: Any) -> Any:
    """``3x + 1`` elementwise, through tuple/list pytree structure —
    breaks exact AND tolerance-based validation for any nonzero
    result."""
    if isinstance(value, (tuple, list)):
        return type(value)(_corrupt_value(v) for v in value)
    return value * 3 + 1


def corrupt(site: str, value: Any, **context: Any) -> Any:
    """Result-carrying injection site: returns ``value`` untouched unless
    a ``corrupt`` rule fires, in which case the result comes back
    numerically wrong so the validation layer must catch it. The site is
    recorded as fired ONLY when the corruption actually applied — a
    value the transform cannot touch is passed through with a loud
    warning, never silently claimed as injected."""
    if _plan is None:
        return value
    rule = _resolve(site, context, ("corrupt",), fire=False)
    if rule is None:
        return value
    try:
        corrupted = _corrupt_value(value)
    except TypeError:
        telemetry.warn(
            f"corrupt rule at {site} cannot corrupt a "
            f"{type(value).__name__}; value passed through UNCORRUPTED"
        )
        return value
    _fired(site, rule)
    return corrupted


def corrupt_row(site: str, row: Dict[str, Any], **context: Any) -> Dict[str, Any]:
    """Row-carrying injection site (the subprocess worker's posted
    result): when a ``corrupt`` rule fires, the row's timing statistics
    are replaced with NaN and it is marked invalid with an attributable
    error — the "corrupted-result numerics" failure a flaky transport
    produces, made deterministic."""
    if _plan is None:
        return row
    if _resolve(site, context, ("corrupt",)) is None:
        return row
    for key in row:
        if key.endswith("time (ms)") or key.startswith("Throughput"):
            row[key] = float("nan")
    row["valid"] = False
    row["error"] = f"CorruptedResult: injected numerics corruption at {site}"
    from ddlb_tpu.faults.classify import classify_error

    row["error_class"] = classify_error(row["error"], valid=False)
    fired = str(row.get("fault_injected") or "")
    row["fault_injected"] = f"{fired},{site}" if fired else site
    return row


# ---------------------------------------------------------------------------
# Retry backoff
# ---------------------------------------------------------------------------


def backoff_delays(base_s: float, retries: int, seed: str = "") -> List[float]:
    """The runner's retry schedule: exponential backoff with full jitter
    (``base * 2^i * (1 + U[0,1))``), deterministically seeded so a
    replayed sweep waits the same way. Pure so tests can pin it."""
    rng = random.Random(f"backoff:{seed}")
    return [
        base_s * (2 ** i) * (1.0 + rng.random()) for i in range(retries)
    ]
