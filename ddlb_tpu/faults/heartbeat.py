"""Worker heartbeat channel: liveness beats over shared memory.

The hung-worker policy before this module had one clock: kill the child
``worker_timeout`` seconds after it STARTED. That conflates two very
different children — a slow-but-alive one (a ctx=8192 row legitimately
compiling for minutes) and a truly hung one (wedged in a collective) —
and sizing the timeout for the slow case means paying the whole budget
for every hang.

The channel is a ``multiprocessing.Value('d')`` holding the child's
last-beat ``time.monotonic()`` stamp — CLOCK_MONOTONIC is system-wide
on the platforms the fleet runs, so parent and child (same host by
construction) read one comparable clock, immune to the NTP steps a
multi-hour capture window will see. The child beats at every phase
boundary
(``benchmark_worker``'s stage marks) and every host-clock timing
iteration — progress points, deliberately NOT a timer thread, because a
daemon timer keeps beating inside a process whose main thread is wedged,
which would defeat hang detection entirely. The parent's kill rule
becomes: dead when ``now - max(start, last_beat) > worker_timeout`` — a
beating child extends its own deadline, a silent one is killed exactly
``worker_timeout`` after its last sign of life.

The ``Value`` is created with ``lock=False``: beats are single aligned
8-byte stores, and a LOCKED value would let a child SIGKILLed mid-beat
orphan the lock and deadlock the parent's next read — the exact
unbounded-hang class this channel exists to eliminate. The no-channel
fast path (every in-process run) is one ``is None`` check.
"""

from __future__ import annotations

import time
from typing import Any, Optional

_channel: Optional[Any] = None


def new_channel(ctx: Any) -> Any:
    """A fresh beat channel for one worker process (``ctx`` is a
    multiprocessing context). The one construction site, so every
    consumer (the warm-worker pool's leases) inherits the load-bearing
    ``lock=False`` choice documented above instead of re-deriving it."""
    return ctx.Value("d", 0.0, lock=False)


def set_channel(channel: Any) -> None:
    """Install this process's beat channel (the subprocess worker entry
    does this with the ``Value`` its parent passed); ``None`` detaches."""
    global _channel
    _channel = channel
    if channel is not None:
        beat()


def beat() -> None:
    """Record a liveness beat (no-op without a channel)."""
    channel = _channel
    if channel is not None:
        channel.value = time.monotonic()


def last_beat(channel: Any) -> float:
    """The child's last beat as ``time.monotonic()`` seconds (0.0 =
    never beat)."""
    return float(channel.value)
