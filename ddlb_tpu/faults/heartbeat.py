"""Worker heartbeat channel: liveness beats over shared memory.

The hung-worker policy before this module had one clock: kill the child
``worker_timeout`` seconds after it STARTED. That conflates two very
different children — a slow-but-alive one (a ctx=8192 row legitimately
compiling for minutes) and a truly hung one (wedged in a collective) —
and sizing the timeout for the slow case means paying the whole budget
for every hang.

The channel is a ``multiprocessing.Value('d')`` holding the child's
last-beat ``time.monotonic()`` stamp — CLOCK_MONOTONIC is system-wide
on the platforms the fleet runs, so parent and child (same host by
construction) read one comparable clock, immune to the NTP steps a
multi-hour capture window will see. The child beats at every phase
boundary
(``benchmark_worker``'s stage marks) and every host-clock timing
iteration — progress points, deliberately NOT a timer thread, because a
daemon timer keeps beating inside a process whose main thread is wedged,
which would defeat hang detection entirely. The parent's kill rule
becomes: dead when ``now - max(start, last_beat) > worker_timeout`` — a
beating child extends its own deadline, a silent one is killed exactly
``worker_timeout`` after its last sign of life.

The ``Value`` is created with ``lock=False``: beats are single aligned
8-byte stores, and a LOCKED value would let a child SIGKILLed mid-beat
orphan the lock and deadlock the parent's next read — the exact
unbounded-hang class this channel exists to eliminate. The no-channel
fast path (every in-process run) is one ``is None`` check.

**File beats** extend the channel beyond shared memory: when
``DDLB_TPU_BEAT_FILE`` names a path, ``beat()`` additionally publishes
the stamp to that file (atomic tmp+rename so a reader never sees a torn
write; throttled to one write per ``FILE_BEAT_INTERVAL_S`` so the
per-iteration beats of a timing loop cost at most ~10 syscall bursts a
second). A shared-memory ``Value`` requires the supervisor to have
FORKED the worker; the file form is what lets the multi-process
launcher (``cli/launch.py --supervise``) watch ranks it merely
spawned — same stamp, same monotonic clock domain (same host by
construction), read with ``read_file_beat``.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional

from ddlb_tpu import envs

#: minimum seconds between file-beat writes (shared-memory beats are
#: never throttled — they are one aligned store)
FILE_BEAT_INTERVAL_S = 0.1

_UNSET = object()

_channel: Optional[Any] = None
#: resolved DDLB_TPU_BEAT_FILE path (None = disabled), lazy like the
#: fault plan so any process that beats self-configures from its env
_file: Any = _UNSET
_file_last_write = 0.0


def new_channel(ctx: Any) -> Any:
    """A fresh beat channel for one worker process (``ctx`` is a
    multiprocessing context). The one construction site, so every
    consumer (the warm-worker pool's leases) inherits the load-bearing
    ``lock=False`` choice documented above instead of re-deriving it."""
    return ctx.Value("d", 0.0, lock=False)


def set_channel(channel: Any) -> None:
    """Install this process's beat channel (the subprocess worker entry
    does this with the ``Value`` its parent passed); ``None`` detaches."""
    global _channel
    _channel = channel
    if channel is not None:
        beat()


def beat() -> None:
    """Record a liveness beat (no-op without a channel or beat file)."""
    now = time.monotonic()
    channel = _channel
    if channel is not None:
        channel.value = now
    path = _file
    if path is _UNSET:
        path = _resolve_file()
    if path is not None:
        _write_file_beat(path, now)


def reset_file() -> None:
    """Re-read ``DDLB_TPU_BEAT_FILE`` on the next beat (test helper)."""
    global _file, _file_last_write
    _file = _UNSET
    _file_last_write = 0.0


def _resolve_file() -> Optional[str]:
    global _file
    _file = envs.get_beat_file() or None
    return _file


def _write_file_beat(path: str, now: float) -> None:
    """Publish ``now`` to the beat file: throttled, atomic (tmp +
    rename — a supervisor's read never sees a torn stamp), and
    per-pid tmp names so two processes of one rank (runner + pool
    child) can share a file, last writer winning."""
    global _file, _file_last_write
    if now - _file_last_write < FILE_BEAT_INTERVAL_S and _file_last_write:
        return
    _file_last_write = now
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(f"{now:.6f}\n")
        os.replace(tmp, path)
    except OSError:
        # a vanished run dir must never crash a beating worker; the
        # supervisor sees the stamp go stale, which is the truth
        _file = None


def read_file_beat(path: str) -> float:
    """The last published file beat as ``time.monotonic()`` seconds
    (0.0 = never beat / unreadable / torn)."""
    try:
        with open(path, encoding="utf-8") as f:
            return float(f.read().strip() or 0.0)
    except (OSError, ValueError):
        return 0.0


def last_beat(channel: Any) -> float:
    """The child's last beat as ``time.monotonic()`` seconds (0.0 =
    never beat)."""
    return float(channel.value)
