"""Collective flight recorder: sequenced progress entries per rank.

When a multi-process world wedges, the operator's question is never
"did it hang" (the watchdog answers that) but **"which rank, at which
collective"** — one rank dying or stalling leaves every peer blocked in
the next collective with nothing pointing back at the culprit. This
module answers it with a black-box flight recorder: every process keeps
a bounded in-memory ring of **sequenced progress entries** — site name,
mesh axes, payload bytes, monotonic start/end — and, when
``DDLB_TPU_FLIGHTREC`` names a shared run directory, appends one
flushed JSON line per transition to a per-rank
``flight-p<rank>.jsonl``. In an SPMD world every rank executes the same
sequence of sites, so the per-rank sequence numbers are directly
comparable: the rank whose last *completed* sequence is lowest is the
lagging rank, and the site its peers are stuck *inside* is the
divergence point. ``analyze_run`` (CLI: ``scripts/flight_report.py``)
computes exactly that join.

Crash-safety contract, each piece load-bearing:

- **Begin lines land before the work**: an entry's ``B`` line is
  appended and flushed *before* the recorded region runs, so a rank
  SIGKILLed (or wedged forever) mid-collective still shows the
  collective it entered — the one fact a post-mortem needs most.
- **Append-only, one line per transition**: no rewrite step exists
  that a crash could corrupt; a torn final line is skipped by the
  reader.
- **Dump on signal / deadline**: ``configure`` installs SIGTERM/SIGUSR1
  handlers (main thread only) that append a ``D`` line carrying the
  dump reason and any in-flight entries, then — for SIGTERM — restore
  the default disposition and re-raise so the exit status still says
  "terminated". The supervised launcher's coordinated abort sends
  SIGTERM first for precisely this reason; its silence deadline is the
  "dump-on-deadline" trigger.
- **Zero overhead unset**: the fast path is one cached ``is None``
  check, same contract as the fault plan and the live stream.

Monotonic clocks only (this module is on the static analyzer's
wall-clock ban list, DDLB102): entries are compared across ranks on
one host, where CLOCK_MONOTONIC is system-wide.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

from ddlb_tpu import envs, telemetry

from contextlib import contextmanager

#: completed entries kept in memory for the dump summary (the file gets
#: every transition regardless; the ring only bounds process memory)
RING_SIZE = 512

_UNSET = object()

_lock = threading.Lock()
#: None = disabled; a dict = active recorder state
_state: Any = _UNSET


def _resolve_state() -> Optional[Dict[str, Any]]:
    """Build (once) the recorder state from the environment: the
    per-rank file handle, the sequence counter, the ring, and the
    signal handlers. Returns None (cached) when the knob is unset."""
    global _state
    with _lock:
        if _state is not _UNSET:
            return _state
        run_dir = envs.get_flightrec_dir()
        if not run_dir:
            _state = None
            return None
        rank = envs.get_process_id()
        path = os.path.join(run_dir, f"flight-p{rank}.jsonl")
        try:
            os.makedirs(run_dir, exist_ok=True)
            fh = open(path, "a", encoding="utf-8")
        except OSError as exc:
            telemetry.warn(
                f"flight recorder disabled: cannot open {path} ({exc})"
            )
            _state = None
            return None
        _state = {
            "fh": fh,
            "path": path,
            "rank": rank,
            "pid": os.getpid(),
            "seq": 0,
            "ring": collections.deque(maxlen=RING_SIZE),
            #: thread ident -> the B-entry dict currently in flight
            "inflight": {},
        }
        _install_handlers()
        atexit.register(_atexit_dump)
        return _state


def reset() -> None:
    """Drop the cached recorder state (test helper; the next record
    re-reads the environment). Does not uninstall signal handlers."""
    global _state
    with _lock:
        state = _state
        if isinstance(state, dict):
            try:
                state["fh"].close()
            except OSError:
                pass  # already closed; nothing left to release
        _state = _UNSET


def enabled() -> bool:
    """True when a run directory is configured (resolving it on first
    call)."""
    state = _state
    if state is _UNSET:
        state = _resolve_state()
    return state is not None


def _emit(state: Dict[str, Any], line: Dict[str, Any]) -> None:
    """Append + flush one transition line (crash-safe unit)."""
    global _state
    try:
        state["fh"].write(json.dumps(line, default=str) + "\n")
        state["fh"].flush()
    except RuntimeError:
        # reentrant call into the buffered writer: a signal-handler
        # dump landed while the main thread was mid-_emit. Drop this
        # one line — the incremental B/E record already covers it —
        # and keep the recorder (and the signal handler's control
        # flow) intact rather than letting CPython's reentrancy
        # RuntimeError escape into arbitrary main-thread code.
        return
    except (OSError, ValueError) as exc:
        telemetry.warn(f"flight recorder write failed ({exc}); disabling")
        _state = None


@contextmanager
def record(
    site: str, axes: str = "", payload_bytes: int = 0, **ctx: Any
):
    """One sequenced progress entry around a collective (or any other
    lock-step region): the ``B`` line is flushed BEFORE the body runs
    (a rank killed inside still shows where), the ``E`` line after.
    No-op (one cached check) when recording is off."""
    state = _state
    if state is _UNSET:
        state = _resolve_state()
    if state is None:
        yield
        return
    with _lock:
        state["seq"] += 1
        seq = state["seq"]
    entry = {
        "seq": seq,
        "ph": "B",
        "site": site,
        "t": time.monotonic(),
        "pid": state["pid"],
        "rank": state["rank"],
    }
    if axes:
        entry["axes"] = axes
    if payload_bytes:
        entry["bytes"] = int(payload_bytes)
    for key, value in ctx.items():
        if value is not None:
            entry[key] = value
    ident = threading.get_ident()
    state["inflight"][ident] = entry
    _emit(state, entry)
    try:
        yield
    finally:
        state["inflight"].pop(ident, None)
        end = {
            "seq": seq,
            "ph": "E",
            "site": site,
            "t": time.monotonic(),
            "pid": state["pid"],
            "rank": state["rank"],
        }
        state["ring"].append({**entry, "t_end": end["t"]})
        _emit(state, end)


def mark(site: str, **ctx: Any) -> None:
    """One instantaneous sequenced entry (phase marks, pool rows) —
    counts as completed immediately."""
    state = _state
    if state is _UNSET:
        state = _resolve_state()
    if state is None:
        return
    with _lock:
        state["seq"] += 1
        seq = state["seq"]
    entry = {
        "seq": seq,
        "ph": "I",
        "site": site,
        "t": time.monotonic(),
        "pid": state["pid"],
        "rank": state["rank"],
    }
    for key, value in ctx.items():
        if value is not None:
            entry[key] = value
    state["ring"].append(dict(entry))
    _emit(state, entry)


def dump(reason: str) -> None:
    """Append a dump marker carrying the reason, the last completed
    sequence, and every in-flight entry — the dump-on-signal /
    dump-on-deadline record. Safe to call from a signal handler (append
    + flush only; no locks beyond the emit)."""
    state = _state
    if not isinstance(state, dict):
        return
    _emit(
        state,
        {
            "ph": "D",
            "reason": reason,
            "t": time.monotonic(),
            "pid": state["pid"],
            "rank": state["rank"],
            "last_seq": state["seq"],
            "inflight": [
                {"seq": e["seq"], "site": e.get("site")}
                for e in state["inflight"].values()
            ],
        },
    )


def _atexit_dump() -> None:
    dump("exit")


def _install_handlers() -> None:
    """SIGTERM/SIGUSR1 dump handlers (main thread only — installing
    from a worker thread raises, in which case the atexit dump and the
    incremental lines still cover the record)."""

    def _on_usr1(signum, frame):
        dump("SIGUSR1")

    def _on_term(signum, frame):
        dump("SIGTERM")
        # restore default and re-raise so the exit status still says
        # "terminated by SIGTERM" to whoever is supervising
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)

    try:
        signal.signal(signal.SIGUSR1, _on_usr1)
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        telemetry.log(
            "flight recorder: not on the main thread; signal-dump "
            "handlers not installed (incremental lines still recorded)"
        )


# ---------------------------------------------------------------------------
# Post-mortem attribution (the scripts/flight_report.py engine)
# ---------------------------------------------------------------------------


def _read_rank_file(path: str) -> List[Dict[str, Any]]:
    """Parse one per-rank JSONL file, skipping torn/corrupt lines."""
    lines: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            data = f.read()
    except OSError:
        return lines
    for raw in data.splitlines():
        raw = raw.strip()
        if not raw:
            continue
        try:
            event = json.loads(raw)
        except ValueError:
            continue  # torn final line mid-append
        if isinstance(event, dict) and "ph" in event:
            lines.append(event)
    return lines


#: public alias: the timeline observatory (observatory/timeline.py)
#: reads per-rank files with the same torn-line tolerance the sequence
#: join uses, so the time join and the sequence join cannot diverge on
#: what counts as a readable entry
read_rank_file = _read_rank_file


def rank_files(run_dir: str) -> Dict[int, str]:
    """``{rank: path}`` for every ``flight-p<rank>.jsonl`` under
    ``run_dir`` — the one discovery rule the sequence join
    (``analyze_run``) and the time join (``observatory/timeline.py``)
    share, so a filename-format change cannot desynchronize them."""
    out: Dict[int, str] = {}
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("flight-p") and name.endswith(".jsonl")):
            continue
        try:
            rank = int(name[len("flight-p"):-len(".jsonl")])
        except ValueError:
            continue
        out[rank] = os.path.join(run_dir, name)
    return out


def dominant_stream(
    events: List[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """One rank's events reduced to the pid stream with the most
    entries — the rank's main process (pool children share the file
    but run their own sequence). Shared by both joins for the same
    cannot-diverge reason as ``rank_files``."""
    by_pid: Dict[Any, List[Dict[str, Any]]] = {}
    for event in events:
        by_pid.setdefault(event.get("pid"), []).append(event)
    if not by_pid:
        return []
    return max(by_pid.values(), key=len)


def _rank_summary(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold one rank's transitions into its progress summary, using the
    pid stream with the most entries (``dominant_stream`` — a rank's
    main process; pool children share the file but run their own
    sequence)."""
    stream = dominant_stream(events)
    if not stream:
        return {
            "last_completed_seq": 0, "inflight": [], "entries": 0,
            "dumps": [], "pid": None, "by_seq": {},
        }
    pid = stream[0].get("pid")
    begun: Dict[int, Dict[str, Any]] = {}
    by_seq: Dict[int, str] = {}
    completed = 0
    dumps: List[str] = []
    for e in stream:
        ph = e.get("ph")
        if ph == "B":
            begun[int(e.get("seq", 0))] = e
            by_seq[int(e.get("seq", 0))] = str(e.get("site", ""))
        elif ph == "E":
            begun.pop(int(e.get("seq", 0)), None)
            completed = max(completed, int(e.get("seq", 0)))
        elif ph == "I":
            completed = max(completed, int(e.get("seq", 0)))
            by_seq[int(e.get("seq", 0))] = str(e.get("site", ""))
        elif ph == "D":
            dumps.append(str(e.get("reason", "")))
    inflight = [
        {"seq": seq, "site": entry.get("site")}
        for seq, entry in sorted(begun.items())
    ]
    # progress orders ranks for the lagging computation: BEGINNING an
    # entry is progress past everything completed (the rank ARRIVED at
    # the collective) but not completion of it — so a rank wedged in
    # seq N outranks a peer that never reached N, and two ranks wedged
    # in the same collective tie
    progress = float(completed)
    if begun:
        progress = max(progress, max(begun) - 0.5)
    return {
        "last_completed_seq": completed,
        "inflight": inflight,
        "entries": len(stream),
        "dumps": dumps,
        "pid": pid,
        "by_seq": by_seq,
        "progress": progress,
    }


def analyze_run(
    run_dir: str, expected_ranks: Optional[int] = None
) -> Dict[str, Any]:
    """Join the per-rank flight files under ``run_dir``: the highest
    common completed sequence, the lagging rank(s), and the divergence
    site. Returns a plain-data report (``scripts/flight_report.py``
    renders it; the supervised launcher prints its headline after a
    coordinated abort)."""
    ranks: Dict[int, Dict[str, Any]] = {}
    for rank, path in rank_files(run_dir).items():
        ranks[rank] = _rank_summary(_read_rank_file(path))
    missing: List[int] = []
    if expected_ranks:
        missing = [r for r in range(expected_ranks) if r not in ranks]
    report: Dict[str, Any] = {
        "run_dir": run_dir,
        "ranks": ranks,
        "missing_ranks": missing,
    }
    if not ranks:
        report["headline"] = f"no flight files under {run_dir}"
        return report
    common = min(s["last_completed_seq"] for s in ranks.values())
    floor = min(s["progress"] for s in ranks.values())
    ahead = [r for r, s in ranks.items() if s["progress"] > floor]
    lagging = sorted(
        r for r, s in ranks.items() if s["progress"] == floor
    )
    report["common_seq"] = common
    # every rank at the same completed seq is not "lagging" — the world
    # diverged inside one collective (or finished cleanly)
    report["lagging_ranks"] = lagging if ahead else []
    divergence = None
    for pool in (lagging if ahead else []), sorted(ahead), sorted(ranks):
        for r in pool:
            if ranks[r]["inflight"]:
                divergence = ranks[r]["inflight"][-1]["site"]
                break
        if divergence:
            break
    if divergence is None and ahead:
        # nobody is stuck (peers may ERROR through a dead-peer
        # collective rather than wedge in it): the divergence point is
        # then the first entry an ahead rank ran past the common seq —
        # the thing the lagging rank never arrived at
        for r in sorted(ahead):
            divergence = ranks[r]["by_seq"].get(common + 1)
            if divergence:
                break
    for s in ranks.values():
        del s["by_seq"]  # per-entry detail: report stays summary-sized
    report["divergence_site"] = divergence
    stuck = sorted(r for r, s in ranks.items() if s["inflight"])
    if missing:
        report["headline"] = (
            f"rank(s) {missing} left no flight file (killed before "
            f"recording anything) — peers stuck"
            + (f" in '{divergence}'" if divergence else "")
        )
    elif ahead and lagging:
        who = lagging[0] if len(lagging) == 1 else lagging
        top = max(s["last_completed_seq"] for s in ranks.values())
        suffix = f" — diverged at '{divergence}'" if divergence else ""
        report["headline"] = (
            f"rank {who} lagging at seq {common} while rank(s) "
            f"{sorted(ahead)} reached {top}{suffix}"
        )
    elif stuck:
        report["headline"] = (
            f"all ranks at seq {common}, in flight in '{divergence}' — "
            f"the collective itself wedged"
        )
    else:
        report["headline"] = (
            f"all ranks completed through seq {common}; no divergence"
        )
    return report
