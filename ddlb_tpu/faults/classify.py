"""Error classification: transient (retry) / degraded (mitigate) /
deterministic (park).

One shared split for every failure-policy consumer — the self-healing
sweep runner retries only transients, the hardware row queue parks
everything else immediately instead of burning its MAX_ATTEMPTS passes,
and the supervised launcher picks its relaunch mode from the class. The
classes:

- **transient**: the failure came from the environment, not the config —
  a hung/killed worker (``TimeoutError``, ``WorkerDied``), allocator
  pressure that a retry with a clean process may dodge
  (``RESOURCE_EXHAUSTED``), transport/runtime flaps (``UNAVAILABLE``,
  ``DEADLINE_EXCEEDED``, broken pipes, spawn failures). Worth a retry
  with backoff.
- **degraded** (ISSUE 15): the failure names a *persistently bad
  component* — a downed/slow link (``link_down``), a peer that went
  silent while its world kept beating (``SlowPeer``: the
  barrier-timeout-with-surviving-peers shape), a persistent-straggler
  indictment. An identical retry hits the same hardware and fails the
  same way; the remedy is the supervised launcher's DEGRADED relaunch
  (world shrunk/remapped around the indicted rank), and the row queue
  parks it like a deterministic failure — re-burning capture windows
  on bad hardware helps nobody.
- **deterministic**: the config itself is wrong or produces wrong
  numbers — ``ValueError``/``TypeError`` from option or shape checks, a
  validation mismatch, corrupted-result numerics. A retry re-pays the
  full cost for the same answer; park immediately.

Classification is substring-based over the recorded error string (the
rows and the queue state both carry stringified errors, not exception
objects), with the degraded patterns checked first (a ``link_down``
raises ``ConnectionError``, which would otherwise read transient — and
relaunching the same world onto the same dead link just fails again),
then the transient ones; an unrecognized error is deterministic — the
conservative default for wall-clock, since a wrongly-parked row costs
one manual retry while a wrongly-retried one burns a capture window.
JAX-free, importable from every process tier.
"""

from __future__ import annotations

TRANSIENT = "transient"
DETERMINISTIC = "deterministic"
DEGRADED = "degraded"

#: substrings marking an error as caused by a persistently degraded
#: component (checked BEFORE the transient patterns — see module
#: docstring): the link_down realization's actual raise site
#: (faults.plan.inject — anchored on the full injected phrase, because
#: a bare "link_down"/"link_slow" would also match the plan VALIDATION
#: ValueErrors, which are deterministic config errors that must park,
#: never trigger a world shrink), the launcher's slow-peer abort, and
#: the health verdict's indictment vocabulary
DEGRADED_PATTERNS = (
    "injected link_down",
    "link is down",
    "SlowPeer",
    "slow peer",
    "persistent straggler",
    "DegradedWorld",
)

#: substrings marking an error as environment-caused and retryable;
#: checked against the stringified error (exception class names prefix
#: the message everywhere this repo records one)
TRANSIENT_PATTERNS = (
    "TimeoutError",
    "WorkerDied",
    "worker spawn failed",
    "RESOURCE_EXHAUSTED",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "DATA_LOSS",
    "ConnectionError",
    "ConnectionResetError",
    "BrokenPipeError",
    "EOFError",
    "heartbeat",
    # distributed-bootstrap flaps: a coordinator that was slow to bind,
    # a rank that raced the rendezvous window, a backend whose client
    # init timed out — the environment's fault, and exactly what the
    # supervised launcher's world-level relaunch exists to absorb
    # (cli/launch.py); a retried bootstrap on a fresh port succeeds
    "coordinator",
    "Unable to initialize backend",
    "Barrier timed out",
    "failed to connect",
    # a collective peer dying mid-op (gloo TCP on the CPU-sim DCN
    # stand-in): the surviving ranks' rows carry this, and a relaunched
    # world clears it
    "Connection closed by peer",
)


def classify_error(error: str, valid: bool = True) -> str:
    """``TRANSIENT``, ``DEGRADED``, ``DETERMINISTIC``, or ``""`` for a
    clean row.

    ``valid=False`` with an empty error string is the runner's soft
    validation failure — deterministic (same inputs, same mismatch).
    """
    error = str(error or "").strip()
    if not error:
        return "" if valid else DETERMINISTIC
    for pattern in DEGRADED_PATTERNS:
        if pattern in error:
            return DEGRADED
    for pattern in TRANSIENT_PATTERNS:
        if pattern in error:
            return TRANSIENT
    return DETERMINISTIC
