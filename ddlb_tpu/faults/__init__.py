"""Fault injection, error classification, and worker heartbeats.

The robustness layer (ISSUE 4): the reference harness blocks forever on
a hung child and has "no retries, no timeouts" (SURVEY.md section 5);
this repo's failure machinery (``worker_timeout``, WorkerDied detection,
the queue's retry-then-park policy) existed but was untestable — nothing
could provoke a failure deterministically. Three cooperating pieces, all
zero-dependency (stdlib only, importable from the JAX-free process
tiers):

- ``inject`` / ``corrupt`` / ``scope`` (faults.plan): named injection
  sites threaded through the stack (compile, worker phases, collective
  entry, subprocess lifecycle), driven by a **seeded, deterministic
  fault plan** from ``DDLB_TPU_FAULT_PLAN`` (inline JSON or a file
  path). Zero overhead when the knob is unset: the fast path is one
  global ``is None`` check.
- ``classify_error`` (faults.classify): the transient / degraded /
  deterministic split the self-healing runner, the hardware row queue
  and the supervised launcher share — only transients (TimeoutError,
  WorkerDied, RESOURCE_EXHAUSTED, ...) are worth a retry; degraded
  failures (a downed/slow link, a slow peer — ISSUE 15) park in the
  queue and trigger the launcher's shrunken relaunch; deterministic
  failures (ValueError, validation mismatch) park immediately instead
  of burning capture windows. The plan's topology-scoped kinds
  (``link_slow`` / ``link_down`` / ``chip_slow``, selected by axis /
  index / direction / factor) realize a degraded component as
  deterministic payload-proportional delays at the collective sites.
- ``heartbeat`` (faults.heartbeat): a cheap shared-memory beat channel
  from subprocess workers — extended with **file beats**
  (``DDLB_TPU_BEAT_FILE``) so a supervisor that merely SPAWNED a rank
  (the multi-process launcher) can watch it too — so a slow-but-alive
  child extends its deadline at every phase boundary while a truly
  hung one is killed ``worker_timeout`` seconds after its last sign of
  life.
- ``flightrec`` (faults.flightrec): the collective flight recorder —
  per-rank sequenced progress entries (collective enter/exit, phase
  marks, pool rows) appended crash-safely under ``DDLB_TPU_FLIGHTREC``,
  joined post-mortem by ``analyze_run`` / ``scripts/flight_report.py``
  to name the lagging rank and the divergence site of a wedged world.

The consumers are ``benchmark.PrimitiveBenchmarkRunner`` (per-row retry
with exponential backoff + jitter, per-impl quarantine),
``scripts/measure_queue.py`` (classifier-aware parking), and the
supervised launcher ``cli/launch.py --supervise`` (cross-rank watchdog,
coordinated abort, classifier-gated world relaunch);
``scripts/chaos_sweep.py`` and ``scripts/chaos_launch.py`` are the
end-to-end demonstrations, and ``docs/source/robustness.rst`` the
operator guide.
"""

from __future__ import annotations

from ddlb_tpu.faults import flightrec, heartbeat
from ddlb_tpu.faults.classify import (
    DETERMINISTIC,
    TRANSIENT,
    classify_error,
)
from ddlb_tpu.faults.plan import (
    FaultPlan,
    FaultRule,
    active,
    backoff_delays,
    corrupt,
    corrupt_row,
    inject,
    load_plan,
    reset,
    reset_counts,
    scope,
    set_fire_listener,
)

__all__ = [
    "DETERMINISTIC",
    "FaultPlan",
    "FaultRule",
    "TRANSIENT",
    "active",
    "backoff_delays",
    "classify_error",
    "corrupt",
    "corrupt_row",
    "flightrec",
    "heartbeat",
    "inject",
    "load_plan",
    "reset",
    "reset_counts",
    "scope",
    "set_fire_listener",
]
