"""Option parsing and scoped-environment utilities shared by all primitives.

Unifies the two byte-identical copies the reference keeps at
/root/reference/ddlb/primitives/TPColumnwise/utils.py:9-132 and
/root/reference/ddlb/primitives/TPRowwise/utils.py:9-132 (SURVEY.md notes the
duplication explicitly) into one module.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict, Mapping, Optional

# Keys consumed by the benchmark layer, silently ignored by primitives
# (reference BENCHMARK_OPTIONS, TPColumnwise/utils.py:34-40).
BENCHMARK_OPTIONS = {"implementation"}


class OptionsManager:
    """Validate per-implementation options against a declared schema.

    Schema contract (reference TPColumnwise/utils.py:34-108): an
    implementation class declares ``DEFAULT_OPTIONS`` (name -> default) and
    ``ALLOWED_VALUES`` (name -> list of allowed values, or a 2-tuple
    ``(min, max)`` numeric range where ``None`` means unbounded). Unknown
    option names and out-of-range values raise ``ValueError``.
    """

    def __init__(
        self,
        defaults: Mapping[str, Any],
        allowed: Optional[Mapping[str, Any]] = None,
    ) -> None:
        self.defaults = dict(defaults)
        self.allowed = dict(allowed or {})
        self.options: Dict[str, Any] = dict(self.defaults)
        #: option names the caller explicitly set (vs. defaulted)
        self.overridden: set = set()

    def parse(self, overrides: Mapping[str, Any]) -> Dict[str, Any]:
        for key, value in overrides.items():
            if key in BENCHMARK_OPTIONS:
                continue
            if key not in self.defaults:
                raise ValueError(
                    f"Unknown option '{key}'. Valid options: "
                    f"{sorted(self.defaults)}"
                )
            self._check_allowed(key, value)
            self.options[key] = value
            self.overridden.add(key)
        return self.options

    def _check_allowed(self, key: str, value: Any) -> None:
        spec = self.allowed.get(key)
        if spec is None:
            return
        if isinstance(spec, tuple) and len(spec) == 2:
            lo, hi = spec
            if not isinstance(value, (int, float)) or isinstance(value, bool):
                raise ValueError(
                    f"Option '{key}' expects a number in range {spec}, "
                    f"got {value!r}"
                )
            if (lo is not None and value < lo) or (hi is not None and value > hi):
                raise ValueError(
                    f"Option '{key}'={value!r} outside allowed range {spec}"
                )
            return
        if value not in spec:
            raise ValueError(
                f"Option '{key}'={value!r} not in allowed values {list(spec)}"
            )

    def get(self, key: str, default: Any = None) -> Any:
        return self.options.get(key, default)

    def __getitem__(self, key: str) -> Any:
        return self.options[key]

    def __contains__(self, key: str) -> bool:
        return key in self.options


class EnvVarGuard:
    """RAII-style scoped environment mutation.

    Reference analogue: TPColumnwise/utils.py:9-31. Usable as a context
    manager (preferred) or relying on ``__del__`` like the reference.
    """

    def __init__(self, values: Mapping[str, str]) -> None:
        self._saved: Dict[str, Optional[str]] = {}
        for key, value in values.items():
            self._saved[key] = os.environ.get(key)
            os.environ[key] = value

    def restore(self) -> None:
        for key, old in self._saved.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old
        self._saved = {}

    def __enter__(self) -> "EnvVarGuard":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.restore()

    def __del__(self) -> None:  # pragma: no cover - GC timing dependent
        try:
            self.restore()
        except Exception as exc:
            # __del__ must not raise, but a swallowed restore failure
            # would leak env mutations into later rows — log it unless
            # the interpreter is already tearing down (where the logger
            # itself may be half-collected)
            if not sys.is_finalizing():
                from ddlb_tpu import telemetry

                telemetry.warn(
                    f"EnvVarGuard restore failed during GC: "
                    f"{type(exc).__name__}: {exc}"
                )
