"""int8 quantized GEMM: the MXU's 2x-throughput path.

The v5e MXU multiplies int8 operands at ~394.5 TOPS — twice the bf16 peak
(197 TFLOPS) — so a GEMM that tolerates ~1% quantization noise can double
its roofline. The reference has no analogue (its dtype map stops at fp16,
/root/reference/ddlb/primitives/TPColumnwise/tp_columnwise.py:63-70); this
is a TPU-first capability: symmetric per-row (A) / per-column (B) dynamic
quantization, an int32-accumulating MXU GEMM, and a dequantizing epilogue
fused by XLA (or performed in-kernel by the Pallas variant).

Measured on the v5e at 8192^3 (device_loop protocol): the XLA int8 path
reaches 377 TOPS (0.96 of the int8 peak, 2.16x the bf16 GEMM measured the
same session); the Pallas kernel 352 TOPS at its (1024, 1024, 1024) block
default. Quantizing A dynamically inside the measured step costs one
bandwidth-bound pass over A (297 TOPS end to end at 8192^3).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddlb_tpu.ops.pallas_compat import CompilerParams

#: int8 symmetric range: values quantize to [-127, 127] (-128 unused so the
#: grid is symmetric and |q*s| <= max|x| exactly)
_QMAX = 127.0


def _quantize(x, axis: int):
    """Symmetric quantization along ``axis``: ``x ~ q * s`` with q int8."""
    xf = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(xf), axis=axis, keepdims=True) / _QMAX
    s = jnp.maximum(s, jnp.float32(1e-30))  # all-zero slice guard
    q = jnp.clip(jnp.round(xf / s), -_QMAX, _QMAX).astype(jnp.int8)
    return q, s


def quantize_rowwise(x):
    """Symmetric per-row quantization of the left operand.

    Returns ``(q [m, k] int8, s [m, 1] float32)``. Row granularity matches
    the GEMM's left operand: every product contributing to output row i
    shares scale ``s[i]``, so dequantization is a rank-1 epilogue.
    """
    return _quantize(x, axis=1)


def quantize_colwise(x):
    """Symmetric per-column quantization for the right operand.

    Returns ``(q [k, n] int8, s [1, n] float32)``.
    """
    return _quantize(x, axis=0)


def quantize_weight_stack(w):
    """Per-output-feature quantization of a stacked weight tensor
    ``[..., k, n]`` (contraction on the second-to-last axis): the
    pre-quantized-weights form for inference-style int8 GEMMs. Returns
    ``(q [..., k, n] int8, s [..., 1, n] float32)`` — each trailing 2-D
    matrix quantized exactly as ``quantize_colwise`` would.
    """
    return _quantize(w, axis=-2)


def quantization_atol(k: int) -> float:
    """Validation tolerance for int8-quantized GEMM over the contract's
    seeded uniform [-1, 1] operands (primitives/base.py _host_operands).

    Error model: quantization noise is uniform within +-s/2 per operand
    element (s ~ 1/127), so one product term carries
    ``eps_a * b + a * eps_b`` with variance ``2 * (s^2/12) * E[x^2]``
    = ``1/(127^2 * 18)`` — summing k independent terms gives
    ``sigma = sqrt(k) / (127 * sqrt(18))`` (~0.17 at k=8192), and the max
    over the m*n output samples sits near 6 sigma (measured 1.19 at
    8192^3). ``sqrt(k)/32`` (~2.83 at k=8192) keeps ~2.4x headroom over
    the measured maximum, covering seed variation and the bf16 output
    rounding term (also O(sqrt(k))).
    """
    return math.sqrt(k) / 32.0


def int8_matmul(aq, bq, sa, sb, *, out_dtype=jnp.bfloat16):
    """``(aq * sa) @ (bq * sb)`` without ever materializing the floats.

    int8 x int8 -> int32 on the MXU, then the rank-1 dequantizing epilogue
    ``acc * sa * sb`` (XLA fuses it into the GEMM's output write).
    """
    acc = jax.lax.dot_general(
        aq, bq, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )
    return (acc.astype(jnp.float32) * sa * sb).astype(out_dtype)


@jax.custom_vjp
def int8_ste_matmul(x, w):
    """``x @ w`` computed on the int8 MXU path, differentiable via the
    straight-through estimator.

    Forward: per-row (token) quantization of ``x``, per-column (feature)
    quantization of ``w``, int8 MXU GEMM, fused dequant — float32 out
    (the callers' ``preferred_element_type=float32`` convention). Because
    row scales are per-row-local and column scales per-column-local, the
    result is BIT-IDENTICAL however the row dimension is batched or
    sharded — which is what lets a single-device oracle reproduce a
    sharded model's int8 forward exactly.

    Backward: standard QAT straight-through — gradients flow as if the
    quantizer were the identity: the f32 cotangent contracts against the
    ORIGINAL operands at full f32 width and only the results downcast to
    the operand dtypes (the same form autodiff gives the unquantized
    ``jnp.matmul(x, w, preferred_element_type=f32)``). 2-D operands only;
    callers flatten leading dims.
    """
    q, s = quantize_rowwise(x)
    qw, sw = quantize_colwise(w)
    return int8_matmul(q, qw, s, sw, out_dtype=jnp.float32)


def _ste_fwd(x, w):
    return int8_ste_matmul(x, w), (x, w)


def _ste_bwd(res, g):
    # the f32 cotangent contracts at full width (as autodiff of the
    # unquantized matmul does) and only the RESULTS downcast — rounding g
    # to bf16 first would add gradient noise the STE contract doesn't have
    x, w = res
    gf = g.astype(jnp.float32)
    dx = jax.lax.dot_general(
        gf,
        w.astype(jnp.float32),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    dw = jax.lax.dot_general(
        x.astype(jnp.float32),
        gf,
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(w.dtype)
    return dx, dw


int8_ste_matmul.defvjp(_ste_fwd, _ste_bwd)


def _int8_kernel(a_ref, b_ref, sa_ref, sb_ref, o_ref, acc_ref):
    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jax.lax.dot_general(
        a_ref[:], b_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _flush():
        o_ref[:] = (
            acc_ref[:].astype(jnp.float32) * sa_ref[:] * sb_ref[:]
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_m", "block_n", "block_k", "out_dtype", "interpret"),
)
def int8_matmul_pallas(
    aq,
    bq,
    sa,
    sb,
    *,
    block_m: int = 1024,
    block_n: int = 1024,
    block_k: int = 1024,
    out_dtype=jnp.bfloat16,
    interpret: bool = False,
):
    """Pallas int8 GEMM with the dequantizing epilogue inside the kernel.

    Same grid/pipeline structure as ``ops.matmul`` (k innermost, int32 VMEM
    accumulator, implicit double buffering); scale vectors ride along as
    per-tile ``[bm, 1]`` / ``[1, bn]`` blocks and are applied once at the
    final k step. block_k defaults larger than the bf16 kernel's — int8
    tiles are half the bytes, and (1024, 1024, 1024) measured best on the
    v5e (352 TOPS at 8192^3).
    """
    m, k = aq.shape
    k2, n = bq.shape
    if k != k2:
        raise ValueError(f"contraction mismatch: {aq.shape} @ {bq.shape}")
    bm, bn, bk = min(block_m, m), min(block_n, n), min(block_k, k)
    if m % bm or n % bn or k % bk:
        raise ValueError(
            f"shape ({m},{n},{k}) not divisible by blocks ({bm},{bn},{bk})"
        )
    grid = (m // bm, n // bn, k // bk)
    return pl.pallas_call(
        _int8_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
            pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
            pl.BlockSpec((bm, 1), lambda i, j, s: (i, 0)),
            pl.BlockSpec((1, bn), lambda i, j, s: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=m * k + k * n + m * n * jnp.dtype(out_dtype).itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(aq, bq, sa, sb)
