"""Pallas tiled GEMM: the framework's hand-written compute kernel.

The reference's compute hot path is cuBLAS-backed ``torch.matmul``
(/root/reference/ddlb/primitives/TPColumnwise/pytorch.py:94-97); the
TPU-native counterpart is a Pallas MXU kernel. Grid order (m, n, k) with k
innermost; a float32 VMEM accumulator carries partial sums across the k
steps and Pallas's pipeline machinery double-buffers the HBM->VMEM tile
fetches so DMA overlaps the MXU (pallas_guide.md "Patterns: Double
Buffering" — here via the implicit grid pipeline rather than manual
semaphores).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddlb_tpu.ops.pallas_compat import CompilerParams


def _matmul_kernel(a_ref, b_ref, o_ref, acc_ref):
    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        a_ref[:], b_ref[:], preferred_element_type=jnp.float32
    )

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _flush():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_m", "block_n", "block_k", "interpret")
)
def matmul(
    a,
    b,
    *,
    block_m: int = 1024,
    block_n: int = 1024,
    block_k: int = 512,
    interpret: bool = False,
):
    """``a [m, k] @ b [k, n]`` on the MXU via Pallas.

    Blocks clamp to the operand shape; shapes must divide evenly by the
    (clamped) blocks — benchmark shapes are powers of two, so the canonical
    sweep (512..16384, /root/reference/scripts/config.json:3-7) always fits.

    Block defaults swept on a real v5e at 8192^3 bf16 (median of 8
    device-loop windows, BASELINE.md round-2 protocol): (1024, 1024, 512)
    reaches 172.6 TFLOPS (0.88 of peak) — parity with XLA's stock matmul
    (174.0 same-day) and well ahead of the round-1 default (512, 512, 1024),
    which measures 156.1. Larger tiles fail VMEM allocation (the f32
    accumulator alone is 4 MB).
    """
    m, k = a.shape
    k2, n = b.shape
    if k != k2:
        raise ValueError(f"contraction mismatch: {a.shape} @ {b.shape}")
    bm, bn, bk = min(block_m, m), min(block_n, n), min(block_k, k)
    if m % bm or n % bn or k % bk:
        raise ValueError(
            f"shape ({m},{n},{k}) not divisible by blocks ({bm},{bn},{bk})"
        )
    grid = (m // bm, n // bn, k // bk)
    return pl.pallas_call(
        _matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
            pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=(m * k + k * n + m * n) * a.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(a, b)
