"""jax-version bridge for the Pallas TPU compiler-params class.

jax >= 0.5 spells it ``pltpu.CompilerParams``; the 0.4.x fleet only has
the old ``pltpu.TPUCompilerParams`` name (same constructor signature).
Every ops kernel resolves the class through here — the same
one-version-bridge contract as ``runtime.shard_map_compat`` — so the
kernels stay written in the modern spelling while the interpret-mode
tests still run on old jax.
"""

from __future__ import annotations

import jax.experimental.pallas.tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)
