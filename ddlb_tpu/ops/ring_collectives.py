"""Pure ring collectives as single Pallas programs (RDMA only, no GEMM).

The communication half of ``ops/collective_matmul.py`` factored out: the
same double-buffered credit-semaphore ring protocol (pallas_guide.md
"Patterns: Ring Collectives" + "Async Remote DMA"), but the payload is
copied/accumulated instead of feeding an MXU pipeline. These kernels
exist so the collectives family can measure the hand-driven ICI path
against XLA's lowered collectives with zero compute in the way — the
kernel-level member of the pure-wire benchmark, the role nvFuser's
executor plays for the reference's fused primitives
(/root/reference/ddlb/primitives/TPColumnwise/fuser.py:102-146).

Both kernels run inside ``shard_map`` over a 1-D ``axis_name`` ring of d
devices, and degrade gracefully to d=1 (self-copy). The ring buffer
rides as an input/output-aliased pair because this toolchain cannot
allocate HBM scratch directly (same note as collective_matmul.py).

Protocol recap (see _ag_matmul_kernel for the original):

- two HBM slots per device; slot t%2 holds the chunk being processed at
  step t while the RDMA forwarding it to the right neighbor's slot
  (t+1)%2 is in flight
- a REGULAR credit semaphore gates sends: the right neighbor signals
  when the target slot is free, preventing the step-t send from landing
  on a buffer still being read for step t-1
- a neighbor barrier before the first RDMA ensures every buffer is
  seeded before anyone writes remotely

Interpreter envelope: the distributed Pallas interpreter emulates the
d-device ring in host threads, and at d=8 it livelocks once the
per-hop RDMA payload grows past ~12 KB when there is no compute
between a send and the matching wait (d<=4 handles 64 KB hops fine,
and the fused kernels — which always have a GEMM in that window — pass
at 32 KB hops; measured 2026-07-31 on the 8-device CPU sim). Protocol
correctness is pinned at d in {2,4,8} on small shards with
``detect_races=True``; realistic payloads are a hardware-only
measurement, like every other kernel in ops/.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddlb_tpu.ops.pallas_compat import CompilerParams

from ddlb_tpu.ops.collective_matmul import _neighbor_barrier


def _ring_ag_kernel(
    a_hbm, buf_in, o_hbm, comm_buf, send_sem, recv_sem, copy_sem,
    credit_sem,
    *, axis_name: str, d: int, interpret: bool = False,
):
    del buf_in  # aliased with comm_buf
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, d)
    left = jax.lax.rem(my - 1 + d, d)
    m_loc = a_hbm.shape[0]

    # seed slot 0 with the local shard; barrier so every neighbor's
    # buffer exists before any remote write
    cp = pltpu.make_async_copy(a_hbm, comm_buf.at[0], copy_sem)
    cp.start()
    cp.wait()
    _neighbor_barrier(axis_name, d)

    def step(t, _):
        slot = jax.lax.rem(t, 2)
        nxt = jax.lax.rem(t + 1, 2)

        @pl.when(t < d - 1)
        def _send():
            @pl.when(t >= 1)
            def _credit_gate():
                pltpu.semaphore_wait(credit_sem, 1)

            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[slot],
                dst_ref=comm_buf.at[nxt],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()

        # while the forward flies, land the chunk we hold in its output
        # rows (chunk (my - t) mod d, same schedule as the AG+GEMM ring)
        chunk = jax.lax.rem(my - t + d, d)
        if interpret:
            # the interpreter cannot DMA into a dynamically sliced ref;
            # it CAN read/write refs wholesale (same note as the fused
            # ring's _gemm_pipeline)
            o_hbm[pl.ds(chunk * m_loc, m_loc), :] = comm_buf[slot]
        else:
            ocp = pltpu.make_async_copy(
                comm_buf.at[slot],
                o_hbm.at[pl.ds(chunk * m_loc, m_loc), :],
                copy_sem,
            )
            ocp.start()
            ocp.wait()

        @pl.when(t < d - 1)
        def _wait():
            pltpu.make_async_copy(
                comm_buf.at[nxt], comm_buf.at[nxt], recv_sem.at[nxt]
            ).wait()
            pltpu.make_async_copy(
                comm_buf.at[slot], comm_buf.at[slot], send_sem.at[slot]
            ).wait()
            pltpu.semaphore_signal(
                credit_sem,
                inc=1,
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        return 0

    jax.lax.fori_loop(0, d, step, 0)
    if d >= 2:
        # one credit is produced but never consumed (the last send needs
        # no gate)
        pltpu.semaphore_wait(credit_sem, 1)


def ring_all_gather(
    a_shard,
    *,
    axis_name: str = "tp",
    axis_size: int,
    interpret: bool = False,
    collective_id: int = 5,
):
    """Ring all-gather: ``a_shard [m/d, k]`` -> ``[m, k]`` on every device.

    Call inside ``shard_map``.
    """
    m_loc, k = a_shard.shape
    space = pltpu.VMEM if interpret else pltpu.ANY
    kernel = functools.partial(
        _ring_ag_kernel, axis_name=axis_name, d=axis_size,
        interpret=bool(interpret),
    )
    buf_init = jnp.zeros((2, m_loc, k), a_shard.dtype)
    out, _ = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((m_loc * axis_size, k), a_shard.dtype),
            jax.ShapeDtypeStruct((2, m_loc, k), a_shard.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
        ),
        input_output_aliases={1: 1},
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),   # send
            pltpu.SemaphoreType.DMA((2,)),   # recv
            pltpu.SemaphoreType.DMA,         # seed + output copies
            pltpu.SemaphoreType.REGULAR,     # buffer-free credits
        ],
        compiler_params=CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interpret,
    )(a_shard, buf_init)
    return out


def _ring_rs_kernel(
    a_hbm, acc_in, o_hbm, acc_buf, send_sem, recv_sem, copy_sem,
    credit_sem,
    *, axis_name: str, d: int, bn: int, interpret: bool = False,
):
    del acc_in  # aliased with acc_buf
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, d)
    left = jax.lax.rem(my - 1 + d, d)
    m_loc, k = a_hbm.shape
    rows = m_loc // d

    _neighbor_barrier(axis_name, d)

    def step(t, _):
        slot = jax.lax.rem(t, 2)
        nxt = jax.lax.rem(t + 1, 2)
        # after d steps each device's accumulator holds its own chunk,
        # fully reduced (same schedule as the GEMM+RS ring)
        chunk = jax.lax.rem(my + d - 1 - t, d)
        a_chunk = a_hbm.at[pl.ds(chunk * rows, rows), :]

        # retire the previous send and free the left neighbor's buffer
        @pl.when(t >= 1)
        def _retire():
            pltpu.make_async_copy(
                acc_buf.at[nxt], acc_buf.at[nxt], send_sem.at[nxt]
            ).wait()
            pltpu.semaphore_signal(
                credit_sem,
                inc=1,
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        # the travelling partial for this step has landed in acc_buf[slot]
        @pl.when(t >= 1)
        def _recv():
            pltpu.make_async_copy(
                acc_buf.at[slot], acc_buf.at[slot], recv_sem.at[slot]
            ).wait()

        # fold our chunk's rows into it (first step initializes)
        if interpret:
            acc_buf[slot] = jnp.where(
                t == 0, a_chunk[...], a_chunk[...] + acc_buf[slot]
            )
        else:

            def add_body(a_ref, acc_ref, o_ref):
                @pl.when(t == 0)
                def _init():
                    o_ref[:] = a_ref[:]

                @pl.when(t > 0)
                def _add():
                    o_ref[:] = a_ref[:] + acc_ref[:]

            pltpu.emit_pipeline(
                add_body,
                grid=(k // bn,),
                in_specs=[
                    pl.BlockSpec((rows, bn), lambda j: (0, j)),
                    pl.BlockSpec((rows, bn), lambda j: (0, j)),
                ],
                out_specs=[pl.BlockSpec((rows, bn), lambda j: (0, j))],
            )(a_chunk, acc_buf.at[slot], acc_buf.at[slot])

        @pl.when(t < d - 1)
        def _send():
            @pl.when(t >= 1)
            def _credit_gate():
                pltpu.semaphore_wait(credit_sem, 1)

            rdma = pltpu.make_async_remote_copy(
                src_ref=acc_buf.at[slot],
                dst_ref=acc_buf.at[nxt],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()

        @pl.when(t == d - 1)
        def _flush():
            cp = pltpu.make_async_copy(acc_buf.at[slot], o_hbm, copy_sem)
            cp.start()
            cp.wait()

        return 0

    jax.lax.fori_loop(0, d, step, 0)
    if d >= 2:
        pltpu.semaphore_wait(credit_sem, 1)


def ring_reduce_scatter(
    a_local,
    *,
    axis_name: str = "tp",
    axis_size: int,
    block_n: int = 512,
    interpret: bool = False,
    collective_id: int = 6,
):
    """Ring reduce-scatter: ``a_local [m/d, k]`` viewed as d chunks
    ``[m/d^2, k]``; chunk j summed across devices lands on device j ->
    ``[m/d^2, k]``. Call inside ``shard_map``.
    """
    m_loc, k = a_local.shape
    if m_loc % axis_size:
        raise ValueError(
            f"local rows {m_loc} not divisible by axis_size={axis_size}"
        )
    rows = m_loc // axis_size
    bn = min(block_n, k)
    if k % bn:
        raise ValueError(f"k={k} not divisible by block {bn}")
    space = pltpu.VMEM if interpret else pltpu.ANY
    kernel = functools.partial(
        _ring_rs_kernel, axis_name=axis_name, d=axis_size, bn=bn,
        interpret=bool(interpret),
    )
    acc_init = jnp.zeros((2, rows, k), a_local.dtype)
    out, _ = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((rows, k), a_local.dtype),
            jax.ShapeDtypeStruct((2, rows, k), a_local.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
        ),
        input_output_aliases={1: 1},
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),   # send
            pltpu.SemaphoreType.DMA((2,)),   # recv
            pltpu.SemaphoreType.DMA,         # output flush
            pltpu.SemaphoreType.REGULAR,     # buffer-free credits
        ],
        compiler_params=CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interpret,
    )(a_local, acc_init)
    return out
