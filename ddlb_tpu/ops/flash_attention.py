"""Pallas flash attention (causal, forward): the attention compute engine.

The einsum attention paths materialize ``[h, q, kv]`` score matrices in
HBM, which caps them at memory bandwidth; this kernel keeps each
``[block_q, block_kv]`` score tile in VMEM with the standard
flash-attention online-softmax accumulator (running max / sum / output),
so the MXU stays fed. Used per-device: the context-parallel
implementations gather or ring the KV blocks and call this kernel on the
local query shard with the right global ``row_offset`` for the causal
mask.

No reference analogue (the reference has no attention operator,
SURVEY.md section 2.5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _online_softmax_update(
    q_blk, k_blk, v_blk, m_prev, l_prev, acc_prev,
    *, scale, q_start, k_start, block_q, block_kv,
):
    """One causal score tile folded into the (m, l, acc) recurrence — the
    single source of the numerically delicate flash update, shared by the
    one-shot and carried-accumulator kernels."""
    q = q_blk.astype(jnp.float32) * scale
    k = k_blk.astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_q, block_kv]
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
    mask = (q_start + rows) >= (k_start + cols)
    s = jnp.where(mask, s, NEG_INF)

    m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = l_prev * alpha + p.sum(-1, keepdims=True)
    acc_new = acc_prev * alpha + jnp.dot(
        p, v_blk.astype(jnp.float32), preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new


def _flash_kernel(
    off_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, block_q: int, block_kv: int,
):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    # runtime scalar (scalar-prefetch arg): the shard's first global query
    # row — one compiled kernel serves every mesh position
    row_offset = off_ref[0]

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # first global query row of this tile vs first key row of that tile:
    # skip tiles entirely in the future (the causal-half FLOP saving)
    q_start = row_offset + qi * block_q
    k_start = kj * block_kv

    @pl.when(q_start + block_q - 1 >= k_start)
    def _compute():
        m_ref[:], l_ref[:], acc_ref[:] = _online_softmax_update(
            q_ref[0], k_ref[0], v_ref[0], m_ref[:], l_ref[:], acc_ref[:],
            scale=scale, q_start=q_start, k_start=k_start,
            block_q=block_q, block_kv=block_kv,
        )

    @pl.when(kj == pl.num_programs(2) - 1)
    def _flush():
        o_ref[0] = (acc_ref[:] / l_ref[:]).astype(o_ref.dtype)


def _flash_chunk_kernel(
    offs_ref, q_ref, k_ref, v_ref, acc_in_ref, m_in_ref, l_in_ref,
    acc_ref, m_ref, l_ref,
    *, scale: float, block_q: int, block_kv: int,
):
    """One KV chunk folded into a carried (acc, m, l) accumulator.

    Same online-softmax math as ``_flash_kernel`` but the accumulator
    state enters and leaves as arrays instead of being created/normalized
    in-kernel — the building block of ring attention, where the chunks
    arrive one ``ppermute`` hop at a time. The output block mapping
    ignores the kv grid dim, so the out refs stay resident across the
    inner iterations and accumulate in place.
    """
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    row_offset = offs_ref[0]  # shard's first global query row
    col_offset = offs_ref[1]  # chunk's first global key row

    @pl.when(kj == 0)
    def _init():
        acc_ref[0] = acc_in_ref[0]
        m_ref[0] = m_in_ref[0]
        l_ref[0] = l_in_ref[0]

    q_start = row_offset + qi * block_q
    k_start = col_offset + kj * block_kv

    @pl.when(q_start + block_q - 1 >= k_start)
    def _compute():
        m_ref[0], l_ref[0], acc_ref[0] = _online_softmax_update(
            q_ref[0], k_ref[0], v_ref[0], m_ref[0], l_ref[0], acc_ref[0],
            scale=scale, q_start=q_start, k_start=k_start,
            block_q=block_q, block_kv=block_kv,
        )


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_q", "block_kv", "interpret"),
)
def flash_attention_chunk(
    q,
    k,
    v,
    carry,
    *,
    scale: float,
    row_offset,
    col_offset,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool = False,
):
    """Fold one KV chunk into a flash accumulator (ring-attention step).

    ``q``: [sq, h, dh]; ``k``/``v``: [skv, h, dh] — the chunk whose global
    key rows start at ``col_offset`` (a runtime scalar, like
    ``row_offset``). ``carry`` is ``(acc, m, l)`` with head-major shapes
    ``[h, sq, dh]``, ``[h, sq, 1]``, ``[h, sq, 1]`` (f32), as produced by
    ``init_flash_carry``. Returns the updated carry; normalize with
    ``finalize_flash_carry`` after the last chunk.
    """
    acc, m_run, l_run = carry
    sq, h, dh = q.shape
    skv = k.shape[0]
    bq, bkv = min(block_q, sq), min(block_kv, skv)
    if sq % bq or skv % bkv:
        raise ValueError(
            f"(sq={sq}, skv={skv}) not divisible by blocks ({bq}, {bkv})"
        )
    qh = q.transpose(1, 0, 2)
    kh = k.transpose(1, 0, 2)
    vh = v.transpose(1, 0, 2)
    kernel = functools.partial(
        _flash_chunk_kernel, scale=scale, block_q=bq, block_kv=bkv
    )
    qspec = pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0))
    kvspec = pl.BlockSpec((1, bkv, dh), lambda hh, i, j, off: (hh, j, 0))
    accspec = pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0))
    mlspec = pl.BlockSpec((1, bq, 1), lambda hh, i, j, off: (hh, i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, sq // bq, skv // bkv),
        in_specs=[qspec, kvspec, kvspec, accspec, mlspec, mlspec],
        out_specs=[accspec, mlspec, mlspec],
    )
    offsets = jnp.stack(
        [
            jnp.asarray(row_offset, jnp.int32),
            jnp.asarray(col_offset, jnp.int32),
        ]
    )
    f32 = jnp.float32
    acc, m_run, l_run = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((h, sq, dh), f32),
            jax.ShapeDtypeStruct((h, sq, 1), f32),
            jax.ShapeDtypeStruct((h, sq, 1), f32),
        ],
        grid_spec=grid_spec,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * h * sq * skv * dh // 2,
            bytes_accessed=(2 * sq + 2 * skv) * h * dh * q.dtype.itemsize
            + 2 * h * sq * (dh + 2) * 4,
            transcendentals=h * sq * skv,
        ),
        interpret=interpret,
    )(offsets, qh, kh, vh, acc, m_run, l_run)
    return acc, m_run, l_run


def init_flash_carry(sq: int, h: int, dh: int):
    """Fresh (acc, m, l) accumulator for ``flash_attention_chunk``."""
    return (
        jnp.zeros((h, sq, dh), jnp.float32),
        jnp.full((h, sq, 1), NEG_INF, jnp.float32),
        jnp.zeros((h, sq, 1), jnp.float32),
    )


def finalize_flash_carry(carry, dtype):
    """Normalize an accumulator into ``[sq, h, dh]`` attention output.
    Fully-masked rows (l == 0) produce zeros, not NaNs."""
    acc, _, l_run = carry
    out = acc / jnp.where(l_run == 0.0, 1.0, l_run)
    return out.transpose(1, 0, 2).astype(dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_q", "block_kv", "interpret"),
)
def flash_attention(
    q,
    k,
    v,
    *,
    scale: float,
    row_offset=0,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool = False,
):
    """Causal flash attention forward.

    ``q``: [sq, h, dh] (global query rows start at ``row_offset``),
    ``k``/``v``: [skv, h, dh]. Returns [sq, h, dh]. ``sq % block_q == 0``
    and ``skv % block_kv == 0`` (benchmark shapes are powers of two).

    Block defaults swept on a real v5e at seq=8192, 8 heads x dh=128 bf16:
    (1024, 1024) reaches ~174 TFLOPS — 12x the einsum attention path.
    """
    sq, h, dh = q.shape
    skv = k.shape[0]
    bq, bkv = min(block_q, sq), min(block_kv, skv)
    if sq % bq or skv % bkv:
        raise ValueError(
            f"(sq={sq}, skv={skv}) not divisible by blocks ({bq}, {bkv})"
        )
    qh = q.transpose(1, 0, 2)  # [h, sq, dh]
    kh = k.transpose(1, 0, 2)
    vh = v.transpose(1, 0, 2)
    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        block_q=bq,
        block_kv=bkv,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, sq // bq, skv // bkv),
        in_specs=[
            pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0)),
            pl.BlockSpec((1, bkv, dh), lambda hh, i, j, off: (hh, j, 0)),
            pl.BlockSpec((1, bkv, dh), lambda hh, i, j, off: (hh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq, dh), jnp.float32),  # output accumulator
            pltpu.VMEM((bq, 1), jnp.float32),   # running max
            pltpu.VMEM((bq, 1), jnp.float32),   # running sum
        ],
    )
    offset = jnp.asarray(row_offset, jnp.int32).reshape(1)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((h, sq, dh), q.dtype),
        grid_spec=grid_spec,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * h * sq * skv * dh // 2,
            bytes_accessed=(2 * sq + 2 * skv) * h * dh * q.dtype.itemsize,
            transcendentals=h * sq * skv,
        ),
        interpret=interpret,
    )(offset, qh, kh, vh)
    return out.transpose(1, 0, 2)
