"""Pallas flash attention (causal, forward + backward): the attention engine.

The einsum attention paths materialize ``[h, q, kv]`` score matrices in
HBM, which caps them at memory bandwidth; these kernels keep each
``[block_q, block_kv]`` score tile in VMEM with the standard
flash-attention online-softmax accumulator (running max / sum / output),
so the MXU stays fed. Used per-device: the context-parallel
implementations gather or ring the KV blocks and call the kernels on the
local query shard with the right global ``row_offset`` for the causal
mask.

Training path: ``flash_attention`` carries a ``jax.custom_vjp`` whose
backward recomputes score tiles from the saved log-sum-exp (the standard
flash backward — no score matrix is ever stored) in two Pallas kernels:
one accumulating dQ over KV tiles, one accumulating dK/dV over Q tiles.
``ring_flash_attention`` lifts the same kernels to a context-parallel
ring under ``shard_map``: K/V chunks circulate via ``ppermute`` in the
forward, and in the backward the dK/dV accumulators travel the ring WITH
their chunks, landing home after one extra hop.

No reference analogue (the reference has no attention operator,
SURVEY.md section 2.5).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddlb_tpu.ops.pallas_compat import CompilerParams

NEG_INF = -1e30


def _online_softmax_update(
    q_blk, k_blk, v_blk, m_prev, l_prev, acc_prev,
    *, scale, q_start, k_start, block_q, block_kv, masked=True, window=0,
):
    """One causal score tile folded into the (m, l, acc) recurrence — the
    single source of the numerically delicate flash update, shared by the
    one-shot and carried-accumulator kernels. ``masked=False`` skips the
    causal mask for tiles statically known to be fully in the past
    (the triangular grid's strictly-below-diagonal tiles). ``window > 0``
    additionally masks keys more than ``window - 1`` positions behind the
    query (sliding-window/local attention)."""
    q = q_blk.astype(jnp.float32) * scale
    k = k_blk.astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_q, block_kv]
    if masked:
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        mask = (q_start + rows) >= (k_start + cols)
        if window:
            mask &= (k_start + cols) > (q_start + rows - window)
        s = jnp.where(mask, s, NEG_INF)

    m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    if masked:
        # a fully-masked row has m_new == NEG_INF, making exp(s - m_new)
        # = 1 for every masked column — zero the masked entries so empty
        # rows keep l == 0 (and flush to zeros) instead of averaging
        # whatever the tile holds (reachable: a window band entirely
        # past the KV span)
        p = jnp.where(mask, p, 0.0)
    l_new = l_prev * alpha + p.sum(-1, keepdims=True)
    acc_new = acc_prev * alpha + jnp.dot(
        p, v_blk.astype(jnp.float32), preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new


def _band_live(q_start, k_start, block_q, block_kv, causal, window):
    """Static-shape predicate: does tile (q_start, k_start) intersect the
    live attention band? Upper edge: not entirely in the future (causal).
    Lower edge: not entirely behind the sliding window."""
    live = True
    if causal:
        live = q_start + block_q - 1 >= k_start
    if window:
        lower = k_start + block_kv - 1 > q_start - window
        live = jnp.logical_and(live, lower) if causal else lower
    return live


def _flash_kernel(
    off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale: float, block_q: int, block_kv: int, causal: bool = True,
    window: int = 0,
):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    # runtime scalar (scalar-prefetch arg): the shard's first global query
    # row — one compiled kernel serves every mesh position
    row_offset = off_ref[0]

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # first global query row of this tile vs first key row of that tile:
    # skip tiles entirely in the future (the causal-half FLOP saving)
    q_start = row_offset + qi * block_q
    k_start = kj * block_kv

    def _do_update():
        m_ref[:], l_ref[:], acc_ref[:] = _online_softmax_update(
            q_ref[0], k_ref[0], v_ref[0], m_ref[:], l_ref[:], acc_ref[:],
            scale=scale, q_start=q_start, k_start=k_start,
            block_q=block_q, block_kv=block_kv,
            masked=causal or bool(window), window=window,
        )

    if causal or window:
        pl.when(
            _band_live(q_start, k_start, block_q, block_kv, causal, window)
        )(_do_update)
    else:
        _do_update()  # non-causal full: every tile is live, no mask

    @pl.when(kj == pl.num_programs(2) - 1)
    def _flush():
        l = l_ref[:]
        o_ref[0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype
        )
        # log-sum-exp of the scaled scores, the only residual the backward
        # needs to rebuild p = exp(s - lse) tile by tile
        lse_ref[0] = jnp.where(
            l == 0.0, NEG_INF, m_ref[:] + jnp.log(l)
        )


def _flash_chunk_kernel(
    offs_ref, q_ref, k_ref, v_ref, acc_in_ref, m_in_ref, l_in_ref,
    acc_ref, m_ref, l_ref,
    *, scale: float, block_q: int, block_kv: int, causal: str = "offset",
    window: int = 0,
):
    """One KV chunk folded into a carried (acc, m, l) accumulator.

    Same online-softmax math as ``_flash_kernel`` but the accumulator
    state enters and leaves as arrays instead of being created/normalized
    in-kernel — the building block of ring attention, where the chunks
    arrive one ``ppermute`` hop at a time. The output block mapping
    ignores the kv grid dim, so the out refs stay resident across the
    inner iterations and accumulate in place.

    ``causal`` statically classifies the chunk's relation to the query
    shard (the ring loop index is static, so callers know it at trace
    time): ``"offset"`` masks from the runtime global offsets (any
    chunk), ``"diagonal"`` masks relative positions only (the t == 0
    chunk, whose row and column offsets are equal), ``"past"`` applies
    no mask at all (every later executed chunk is strictly in the past).
    """
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    row_offset = offs_ref[0]  # shard's first global query row
    col_offset = offs_ref[1]  # chunk's first global key row

    @pl.when(kj == 0)
    def _init():
        acc_ref[0] = acc_in_ref[0]
        m_ref[0] = m_in_ref[0]
        l_ref[0] = l_in_ref[0]

    if causal == "offset":
        q_start = row_offset + qi * block_q
        k_start = col_offset + kj * block_kv
    else:
        # relative coordinates: equal offsets cancel ("diagonal") or the
        # mask is vacuous ("past")
        q_start = qi * block_q
        k_start = kj * block_kv

    def _update():
        m_ref[0], l_ref[0], acc_ref[0] = _online_softmax_update(
            q_ref[0], k_ref[0], v_ref[0], m_ref[0], l_ref[0], acc_ref[0],
            scale=scale, q_start=q_start, k_start=k_start,
            block_q=block_q, block_kv=block_kv,
            masked=causal != "past", window=window,
        )

    if causal == "past":
        _update()  # every tile fully live: no skip predicate, no mask
    else:
        # live-band skip on both edges: causal upper, window lower
        pl.when(
            _band_live(q_start, k_start, block_q, block_kv, True, window)
        )(_update)


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "block_q", "block_kv", "interpret", "causal", "window",
    ),
)
def flash_attention_chunk(
    q,
    k,
    v,
    carry,
    *,
    scale: float,
    row_offset,
    col_offset,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool = False,
    causal: str = "offset",
    window: int = 0,
):
    """Fold one KV chunk into a flash accumulator (ring-attention step).

    ``q``: [sq, h, dh]; ``k``/``v``: [skv, h_kv, dh] (``h_kv < h`` is
    GQA — grouped query heads share the chunk's kv head straight from
    the head index map) — the chunk whose global key rows start at
    ``col_offset`` (a runtime scalar, like ``row_offset``). ``carry`` is
    ``(acc, m, l)`` with head-major shapes ``[h, sq, dh]``,
    ``[h, sq, 1]``, ``[h, sq, 1]`` (f32), as produced by
    ``init_flash_carry``. Returns the updated carry; normalize with
    ``finalize_flash_carry`` after the last chunk.
    """
    acc, m_run, l_run = carry
    sq, h, dh = q.shape
    skv = k.shape[0]
    G = _gqa_group(q, k)
    bq, bkv = min(block_q, sq), min(block_kv, skv)
    if sq % bq or skv % bkv:
        raise ValueError(
            f"(sq={sq}, skv={skv}) not divisible by blocks ({bq}, {bkv})"
        )
    qh = q.transpose(1, 0, 2)
    kh = k.transpose(1, 0, 2)
    vh = v.transpose(1, 0, 2)
    if causal not in ("offset", "diagonal", "past"):
        raise ValueError(f"unknown causal mode {causal!r}")
    if window and causal == "past":
        raise ValueError(
            "window composes with causal='offset'/'diagonal' (a 'past' "
            "chunk may be partially behind the band and needs the mask)"
        )
    kernel = functools.partial(
        _flash_chunk_kernel, scale=scale, block_q=bq, block_kv=bkv,
        causal=causal, window=window,
    )
    qspec = pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0))
    kvspec = pl.BlockSpec((1, bkv, dh), lambda hh, i, j, off: (hh // G, j, 0))
    accspec = pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0))
    mlspec = pl.BlockSpec((1, bq, 1), lambda hh, i, j, off: (hh, i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, sq // bq, skv // bkv),
        in_specs=[qspec, kvspec, kvspec, accspec, mlspec, mlspec],
        out_specs=[accspec, mlspec, mlspec],
    )
    offsets = jnp.stack(
        [
            jnp.asarray(row_offset, jnp.int32),
            jnp.asarray(col_offset, jnp.int32),
        ]
    )
    f32 = jnp.float32
    acc, m_run, l_run = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((h, sq, dh), f32),
            jax.ShapeDtypeStruct((h, sq, 1), f32),
            jax.ShapeDtypeStruct((h, sq, 1), f32),
        ],
        grid_spec=grid_spec,
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * h * sq * skv * dh // 2,
            bytes_accessed=(2 * sq + 2 * skv) * h * dh * q.dtype.itemsize
            + 2 * h * sq * (dh + 2) * 4,
            transcendentals=h * sq * skv,
        ),
        interpret=interpret,
    )(offsets, qh, kh, vh, acc, m_run, l_run)
    return acc, m_run, l_run


def init_flash_carry(sq: int, h: int, dh: int):
    """Fresh (acc, m, l) accumulator for ``flash_attention_chunk``."""
    return (
        jnp.zeros((h, sq, dh), jnp.float32),
        jnp.full((h, sq, 1), NEG_INF, jnp.float32),
        jnp.zeros((h, sq, 1), jnp.float32),
    )


def finalize_flash_carry(carry, dtype):
    """Normalize an accumulator into ``[sq, h, dh]`` attention output.
    Fully-masked rows (l == 0) produce zeros, not NaNs."""
    acc, _, l_run = carry
    out = acc / jnp.where(l_run == 0.0, 1.0, l_run)
    return out.transpose(1, 0, 2).astype(dtype)


def _use_triangular(row_offset, sq, skv) -> bool:
    """The causal iteration space is a STATIC staircase-triangle exactly
    when the query block starts at global row 0 (python-int offset, so the
    live-tile set is known at trace time) and Q and KV cover the same
    square in element space. Masked-out tiles are then dropped from the
    grid entirely — a rectangular grid merely predicates their compute off
    but still pays their K/V prefetch DMA and grid step (~2x the needed
    steps). Blocks need NOT be square: a wider kv block halves the
    online-softmax rescale chain per unit of work."""
    return (
        isinstance(row_offset, (int, np.integer))
        and row_offset == 0
        and sq == skv
    )


def _last_kj(qi, bq, bkv):
    """Last live kv tile of query-tile row ``qi`` (static blocks, offset
    0): the tile containing column ``qi*bq + bq - 1``."""
    return (qi * bq + bq - 1) // bkv


def _first_qi(kj, bq, bkv):
    """First live q tile of kv-tile column ``kj``: the row containing
    element row ``kj*bkv``."""
    return (kj * bkv) // bq


def _tile_needs_mask(qi, kj, bq, bkv):
    """A tile straddles the causal boundary (so its update must mask)
    unless every element is visible; the worst case is the tile's
    top-right element (first q row, last kv column), visible iff
    ``qi*bq >= kj*bkv + bkv - 1``."""
    return (qi * bq) < ((kj + 1) * bkv - 1)


def _tri_maps_lower(nq: int, bq: int, bkv: int):
    """Row-major enumeration of live tiles {(qi, kj): kj <= last_kj(qi)}
    (kj innermost — the kv-accumulation order the fwd/dQ kernels need):
    int32 arrays ``qi_of[t]``, ``kj_of[t]`` for the scalar-prefetch index
    maps."""
    counts = [(_last_kj(i, bq, bkv) + 1) for i in range(nq)]
    qi = np.repeat(np.arange(nq), counts)
    kj = np.concatenate([np.arange(c) for c in counts])
    return jnp.asarray(qi, jnp.int32), jnp.asarray(kj, jnp.int32)


def _tri_maps_upper(nkv: int, nq: int, bq: int, bkv: int):
    """Column-major enumeration of the same live set
    {(kj, qi): qi >= first_qi(kj)} (qi innermost) for the dK/dV kernel,
    which accumulates over q tiles."""
    firsts = [_first_qi(j, bq, bkv) for j in range(nkv)]
    kj = np.repeat(np.arange(nkv), [nq - f for f in firsts])
    qi = np.concatenate([np.arange(f, nq) for f in firsts])
    return jnp.asarray(kj, jnp.int32), jnp.asarray(qi, jnp.int32)


def _flash_kernel_tri(
    qi_ref, kj_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale: float, block_q: int, block_kv: int,
):
    """Triangular-grid forward: one grid step per LIVE causal tile.

    Same math as ``_flash_kernel`` with the (qi, kj) pair decoded from the
    scalar-prefetched live-tile maps; init fires at each query row's first
    kv tile (kj == 0), flush at its last live tile. Only tiles straddling
    the causal boundary apply the mask — fully-past tiles are statically
    live."""
    t = pl.program_id(1)
    qi = qi_ref[t]
    kj = kj_ref[t]
    boundary = _tile_needs_mask(qi, kj, block_q, block_kv)
    last = _last_kj(qi, block_q, block_kv)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _update(masked):
        m_ref[:], l_ref[:], acc_ref[:] = _online_softmax_update(
            q_ref[0], k_ref[0], v_ref[0], m_ref[:], l_ref[:], acc_ref[:],
            scale=scale, q_start=qi * block_q, k_start=kj * block_kv,
            block_q=block_q, block_kv=block_kv, masked=masked,
        )

    @pl.when(boundary)
    def _diag():
        _update(True)

    @pl.when(jnp.logical_not(boundary))
    def _below():
        _update(False)

    @pl.when(kj == last)
    def _flush():
        l = l_ref[:]
        o_ref[0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype
        )
        lse_ref[0] = jnp.where(
            l == 0.0, NEG_INF, m_ref[:] + jnp.log(l)
        )


def _gqa_group(q, k):
    """Query-heads-per-kv-head ratio G (1 = MHA). Shapes are head-minor:
    ``q [sq, h, dh]``, ``k [skv, h_kv, dh]``."""
    h, h_kv = q.shape[1], k.shape[1]
    if h % h_kv:
        raise ValueError(
            f"n_heads={h} not divisible by n_kv_heads={h_kv} (GQA groups)"
        )
    return h // h_kv


def _flash_forward(q, k, v, row_offset, scale, block_q, block_kv, interpret,
                   causal=True, window=0):
    """Forward pallas call; returns ``(o [sq, h, dh], lse [h, sq, 1] f32)``.

    GQA: ``k``/``v`` may carry ``h_kv = h/G`` heads — query head ``hh``
    reads kv head ``hh // G`` straight from the BlockSpec index map, so
    grouped heads share one VMEM-resident KV tile and the kernel body is
    unchanged. ``causal=False`` visits every tile unmasked.
    """
    sq, h, dh = q.shape
    skv = k.shape[0]
    G = _gqa_group(q, k)
    bq, bkv = min(block_q, sq), min(block_kv, skv)
    if sq % bq or skv % bkv:
        raise ValueError(
            f"(sq={sq}, skv={skv}) not divisible by blocks ({bq}, {bkv})"
        )
    qh = q.transpose(1, 0, 2)  # [h, sq, dh]
    kh = k.transpose(1, 0, 2)  # [h_kv, skv, dh]
    vh = v.transpose(1, 0, 2)
    out_shape = [
        jax.ShapeDtypeStruct((h, sq, dh), q.dtype),
        jax.ShapeDtypeStruct((h, sq, 1), jnp.float32),
    ]
    scratch_shapes = [
        pltpu.VMEM((bq, dh), jnp.float32),  # output accumulator
        pltpu.VMEM((bq, 1), jnp.float32),   # running max
        pltpu.VMEM((bq, 1), jnp.float32),   # running sum
    ]
    if causal and not window and _use_triangular(row_offset, sq, skv):
        n = sq // bq
        qi_of, kj_of = _tri_maps_lower(n, bq, bkv)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(h, int(qi_of.shape[0])),
            in_specs=[
                pl.BlockSpec((1, bq, dh), lambda hh, t, qi, kj: (hh, qi[t], 0)),
                pl.BlockSpec(
                    (1, bkv, dh), lambda hh, t, qi, kj: (hh // G, kj[t], 0)
                ),
                pl.BlockSpec(
                    (1, bkv, dh), lambda hh, t, qi, kj: (hh // G, kj[t], 0)
                ),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, dh), lambda hh, t, qi, kj: (hh, qi[t], 0)),
                pl.BlockSpec((1, bq, 1), lambda hh, t, qi, kj: (hh, qi[t], 0)),
            ],
            scratch_shapes=scratch_shapes,
        )
        out, lse = pl.pallas_call(
            functools.partial(
                _flash_kernel_tri, scale=scale, block_q=bq, block_kv=bkv
            ),
            out_shape=out_shape,
            grid_spec=grid_spec,
            compiler_params=CompilerParams(
                dimension_semantics=("parallel", "arbitrary"),
            ),
            cost_estimate=pl.CostEstimate(
                flops=4 * h * sq * skv * dh // 2,
                bytes_accessed=(2 * sq + 2 * skv) * h * dh * q.dtype.itemsize,
                transcendentals=h * sq * skv // 2,
            ),
            interpret=interpret,
        )(qi_of, kj_of, qh, kh, vh)
        return out.transpose(1, 0, 2), lse
    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        block_q=bq,
        block_kv=bkv,
        causal=causal,
        window=window,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, sq // bq, skv // bkv),
        in_specs=[
            pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0)),
            pl.BlockSpec((1, bkv, dh), lambda hh, i, j, off: (hh // G, j, 0)),
            pl.BlockSpec((1, bkv, dh), lambda hh, i, j, off: (hh // G, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda hh, i, j, off: (hh, i, 0)),
        ],
        scratch_shapes=scratch_shapes,
    )
    offset = jnp.asarray(row_offset, jnp.int32).reshape(1)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid_spec=grid_spec,
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * h * sq * skv * dh // 2,
            bytes_accessed=(2 * sq + 2 * skv) * h * dh * q.dtype.itemsize,
            transcendentals=h * sq * skv,
        ),
        interpret=interpret,
    )(offset, qh, kh, vh)
    return out.transpose(1, 0, 2), lse


# -- backward kernels ---------------------------------------------------------


def _recompute_p(q_blk, k_blk, lse_blk, *, scale, q_start, k_start,
                 block_q, block_kv, masked=True, window=0):
    """Rebuild one probability tile from the saved log-sum-exp:
    ``p = exp(scale * q k^T - lse)`` with the causal (and sliding-window)
    mask re-applied (``masked=False`` for tiles statically known fully
    inside the live band)."""
    s = jax.lax.dot_general(
        q_blk.astype(jnp.float32) * scale,
        k_blk.astype(jnp.float32),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if masked:
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        mask = (q_start + rows) >= (k_start + cols)
        if window:
            mask &= (k_start + cols) > (q_start + rows - window)
        s = jnp.where(mask, s, NEG_INF)
        # empty rows carry lse == NEG_INF; exp(NEG_INF - NEG_INF) would
        # be 1 — zero the masked entries explicitly (mirrors the forward)
        return jnp.where(mask, jnp.exp(s - lse_blk), 0.0)
    return jnp.exp(s - lse_blk)


def _dq_tile_update(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_acc_ref,
    *, scale, q_start, k_start, block_q, block_kv, masked=True, window=0,
):
    """Fold one score tile into the dQ accumulator:
    ``dq += scale * ds @ k`` with ``ds = p * (do v^T - delta)`` — the
    single source shared by the rectangular and triangular kernels."""
    p = _recompute_p(
        q_ref[0], k_ref[0], lse_ref[0], scale=scale,
        q_start=q_start, k_start=k_start,
        block_q=block_q, block_kv=block_kv, masked=masked, window=window,
    )
    do = do_ref[0].astype(jnp.float32)
    dp = jax.lax.dot_general(
        do, v_ref[0].astype(jnp.float32),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bq, bkv]
    ds = p * (dp - delta_ref[0])
    dq_acc_ref[:] += scale * jnp.dot(
        ds, k_ref[0].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )


def _dkv_tile_update(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_acc_ref, dv_acc_ref,
    *, scale, q_start, k_start, block_q, block_kv, masked=True, window=0,
):
    """Fold one score tile into the dK/dV accumulators:
    ``dv += p^T @ do``; ``dk += scale * ds^T @ q`` (shared by the
    rectangular and triangular kernels)."""
    p = _recompute_p(
        q_ref[0], k_ref[0], lse_ref[0], scale=scale,
        q_start=q_start, k_start=k_start,
        block_q=block_q, block_kv=block_kv, masked=masked, window=window,
    )
    do = do_ref[0].astype(jnp.float32)
    dv_acc_ref[:] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # p^T @ do -> [bkv, dh]
    dp = jax.lax.dot_general(
        do, v_ref[0].astype(jnp.float32),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_ref[0])
    dk_acc_ref[:] += scale * jax.lax.dot_general(
        ds, q_ref[0].astype(jnp.float32),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # ds^T @ q -> [bkv, dh]


def _flash_bwd_dq_kernel(
    offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dq_acc_ref,
    *, scale: float, block_q: int, block_kv: int, masked: bool = True,
    gated: bool = True, window: int = 0,
):
    """dQ accumulated over KV tiles (inner grid dim). ``gated=False``
    (non-causal) visits every tile."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    row_offset = offs_ref[0]
    col_offset = offs_ref[1]

    @pl.when(kj == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    q_start = row_offset + qi * block_q
    k_start = col_offset + kj * block_kv

    def _do_update():
        _dq_tile_update(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_acc_ref,
            scale=scale, q_start=q_start, k_start=k_start,
            block_q=block_q, block_kv=block_kv, masked=masked,
            window=window,
        )

    if gated or window:
        pl.when(
            _band_live(q_start, k_start, block_q, block_kv, gated, window)
        )(_do_update)
    else:
        _do_update()

    @pl.when(kj == pl.num_programs(2) - 1)
    def _flush():
        dq_ref[0] = dq_acc_ref[:]


def _flash_bwd_dkv_kernel(
    offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
    *, scale: float, block_q: int, block_kv: int, masked: bool = True,
    gated: bool = True, window: int = 0,
):
    """dK/dV accumulated over Q tiles (inner grid dim)."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    row_offset = offs_ref[0]
    col_offset = offs_ref[1]

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    q_start = row_offset + qi * block_q
    k_start = col_offset + kj * block_kv

    def _do_update():
        _dkv_tile_update(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            dk_acc_ref, dv_acc_ref,
            scale=scale, q_start=q_start, k_start=k_start,
            block_q=block_q, block_kv=block_kv, masked=masked,
            window=window,
        )

    if gated or window:
        pl.when(
            _band_live(q_start, k_start, block_q, block_kv, gated, window)
        )(_do_update)
    else:
        _do_update()

    @pl.when(qi == pl.num_programs(2) - 1)
    def _flush():
        dk_ref[0] = dk_acc_ref[:]
        dv_ref[0] = dv_acc_ref[:]


def _flash_bwd_dq_kernel_tri(
    qi_ref, kj_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dq_acc_ref,
    *, scale: float, block_q: int, block_kv: int,
):
    """Triangular-grid dQ: one step per live tile, kv innermost."""
    t = pl.program_id(1)
    qi = qi_ref[t]
    kj = kj_ref[t]
    boundary = _tile_needs_mask(qi, kj, block_q, block_kv)

    @pl.when(kj == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    def _update(masked):
        _dq_tile_update(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_acc_ref,
            scale=scale, q_start=qi * block_q, k_start=kj * block_kv,
            block_q=block_q, block_kv=block_kv, masked=masked,
        )

    @pl.when(boundary)
    def _diag():
        _update(True)

    @pl.when(jnp.logical_not(boundary))
    def _below():
        _update(False)

    @pl.when(kj == _last_kj(qi, block_q, block_kv))
    def _flush():
        dq_ref[0] = dq_acc_ref[:]


def _flash_bwd_dkv_kernel_tri(
    kj_ref, qi_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
    *, scale: float, block_q: int, block_kv: int, n_q: int,
):
    """Triangular-grid dK/dV: column-major over the live set (q tiles
    innermost); init at the column's first live row, flush at the last
    q tile."""
    t = pl.program_id(1)
    kj = kj_ref[t]
    qi = qi_ref[t]
    boundary = _tile_needs_mask(qi, kj, block_q, block_kv)

    @pl.when(qi == _first_qi(kj, block_q, block_kv))
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    def _update(masked):
        _dkv_tile_update(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            dk_acc_ref, dv_acc_ref,
            scale=scale, q_start=qi * block_q, k_start=kj * block_kv,
            block_q=block_q, block_kv=block_kv, masked=masked,
        )

    @pl.when(boundary)
    def _diag():
        _update(True)

    @pl.when(jnp.logical_not(boundary))
    def _above():
        _update(False)

    @pl.when(qi == n_q - 1)
    def _flush():
        dk_ref[0] = dk_acc_ref[:]
        dv_ref[0] = dv_acc_ref[:]


def flash_attention_bwd(
    q, k, v, o, lse, do,
    *,
    scale: float,
    row_offset,
    col_offset,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool = False,
    causal: str = "offset",
    window: int = 0,
):
    """Flash backward against one KV span: returns f32 ``(dq, dk, dv)``.

    ``q``/``o``/``do``: [sq, h, dh] (global rows start at ``row_offset``),
    ``k``/``v``: [skv, h_kv, dh] (global rows start at ``col_offset``;
    ``h_kv < h`` is GQA — dK/dV come back with ``h_kv`` heads, the
    per-query-head contributions group-summed), ``lse``: [h, sq, 1] f32
    log-sum-exp of the GLOBAL softmax (so per-chunk calls compose: each
    chunk's ds tiles are exact slices of the global backward). Two pallas
    calls — one per accumulation direction — each recomputing its score
    tiles in VMEM from ``lse``. ``causal='none'`` disables mask and
    tile-skip gates (bidirectional attention).
    """
    sq, h, dh = q.shape
    skv = k.shape[0]
    h_kv = k.shape[1]
    G = _gqa_group(q, k)
    bq, bkv = min(block_q, sq), min(block_kv, skv)
    if sq % bq or skv % bkv:
        raise ValueError(
            f"(sq={sq}, skv={skv}) not divisible by blocks ({bq}, {bkv})"
        )
    qh = q.transpose(1, 0, 2)
    kh = k.transpose(1, 0, 2)
    vh = v.transpose(1, 0, 2)
    doh = do.transpose(1, 0, 2)

    def _group_sum(dkv_h):
        """[h, skv, dh] per-query-head grads -> [skv, h_kv, dh]."""
        if G == 1:
            return dkv_h.transpose(1, 0, 2)
        return dkv_h.reshape(h_kv, G, skv, dh).sum(axis=1).transpose(1, 0, 2)
    # delta = rowsum(do * o): the softmax-jacobian correction term, cheap
    # elementwise reduce left to XLA
    delta = jnp.sum(
        doh.astype(jnp.float32) * o.transpose(1, 0, 2).astype(jnp.float32),
        axis=-1,
        keepdims=True,
    )  # [h, sq, 1]
    f32 = jnp.float32
    if causal not in ("offset", "diagonal", "past", "none"):
        raise ValueError(f"unknown causal mode {causal!r}")
    if window and causal != "offset":
        raise ValueError(
            "window composes with causal='offset' only (the ring-chunk "
            "modes have no windowed callers)"
        )
    if causal == "diagonal" and sq == skv:
        # the diagonal chunk in relative coordinates IS the static
        # zero-offset square case: take the triangular grids
        row_offset, col_offset = 0, 0
    if (
        causal != "none"
        and not window
        and _use_triangular(row_offset, sq, skv)
        and isinstance(col_offset, (int, np.integer))
        and col_offset == 0
    ):
        n = sq // bq
        nkv = skv // bkv
        qspec_t = pl.BlockSpec((1, bq, dh), lambda hh, t, a, b: (hh, a[t], 0))
        kvspec_t = pl.BlockSpec(
            (1, bkv, dh), lambda hh, t, a, b: (hh // G, b[t], 0)
        )
        mlspec_t = pl.BlockSpec((1, bq, 1), lambda hh, t, a, b: (hh, a[t], 0))
        qi_of, kj_of = _tri_maps_lower(n, bq, bkv)
        tri = int(qi_of.shape[0])
        dq = pl.pallas_call(
            functools.partial(
                _flash_bwd_dq_kernel_tri, scale=scale, block_q=bq, block_kv=bkv
            ),
            out_shape=jax.ShapeDtypeStruct((h, sq, dh), f32),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(h, tri),
                in_specs=[qspec_t, kvspec_t, kvspec_t, qspec_t, mlspec_t, mlspec_t],
                out_specs=qspec_t,
                scratch_shapes=[pltpu.VMEM((bq, dh), f32)],
            ),
            compiler_params=CompilerParams(
                dimension_semantics=("parallel", "arbitrary"),
            ),
            cost_estimate=pl.CostEstimate(
                flops=6 * h * sq * skv * dh // 2,
                bytes_accessed=(2 * sq + 2 * skv) * h * dh * q.dtype.itemsize,
                transcendentals=h * sq * skv // 2,
            ),
            interpret=interpret,
        )(qi_of, kj_of, qh, kh, vh, doh, lse, delta)

        # dK/dV: column-major over the triangle, q tiles innermost; the
        # index maps swap roles (a = kj enumeration, b = qi enumeration)
        kj_of2, qi_of2 = _tri_maps_upper(nkv, n, bq, bkv)
        tri2 = int(kj_of2.shape[0])
        qspec_t2 = pl.BlockSpec((1, bq, dh), lambda hh, t, a, b: (hh, b[t], 0))
        kvspec_t2 = pl.BlockSpec(
            (1, bkv, dh), lambda hh, t, a, b: (hh // G, a[t], 0)
        )
        # dK/dV outputs stay per QUERY head (grid over h; grouped heads
        # sum outside) — only the k/v INPUT maps fold the group
        kvspec_t2_out = pl.BlockSpec(
            (1, bkv, dh), lambda hh, t, a, b: (hh, a[t], 0)
        )
        mlspec_t2 = pl.BlockSpec((1, bq, 1), lambda hh, t, a, b: (hh, b[t], 0))
        dk, dv = pl.pallas_call(
            functools.partial(
                _flash_bwd_dkv_kernel_tri,
                scale=scale, block_q=bq, block_kv=bkv, n_q=n,
            ),
            out_shape=[
                jax.ShapeDtypeStruct((h, skv, dh), f32),
                jax.ShapeDtypeStruct((h, skv, dh), f32),
            ],
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(h, tri2),
                in_specs=[qspec_t2, kvspec_t2, kvspec_t2, qspec_t2, mlspec_t2, mlspec_t2],
                out_specs=[kvspec_t2_out, kvspec_t2_out],
                scratch_shapes=[
                    pltpu.VMEM((bkv, dh), f32),
                    pltpu.VMEM((bkv, dh), f32),
                ],
            ),
            compiler_params=CompilerParams(
                dimension_semantics=("parallel", "arbitrary"),
            ),
            cost_estimate=pl.CostEstimate(
                flops=4 * h * sq * skv * dh // 2,
                bytes_accessed=(2 * sq + 2 * skv) * h * dh * q.dtype.itemsize,
                transcendentals=h * sq * skv // 2,
            ),
            interpret=interpret,
        )(kj_of2, qi_of2, qh, kh, vh, doh, lse, delta)
        return (
            dq.transpose(1, 0, 2),
            _group_sum(dk),
            _group_sum(dv),
        )
    offsets = jnp.stack(
        [jnp.asarray(row_offset, jnp.int32), jnp.asarray(col_offset, jnp.int32)]
    )
    qspec = pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0))
    kvspec = pl.BlockSpec((1, bkv, dh), lambda hh, i, j, off: (hh // G, j, 0))
    mlspec = pl.BlockSpec((1, bq, 1), lambda hh, i, j, off: (hh, i, 0))

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, block_q=bq, block_kv=bkv,
            masked=causal not in ("past", "none"), gated=causal != "none",
            window=window,
        ),
        out_shape=jax.ShapeDtypeStruct((h, sq, dh), f32),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(h, sq // bq, skv // bkv),
            in_specs=[qspec, kvspec, kvspec, qspec, mlspec, mlspec],
            out_specs=qspec,
            scratch_shapes=[pltpu.VMEM((bq, dh), f32)],
        ),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=6 * h * sq * skv * dh // 2,
            bytes_accessed=(2 * sq + 2 * skv) * h * dh * q.dtype.itemsize,
            transcendentals=h * sq * skv,
        ),
        interpret=interpret,
    )(offsets, qh, kh, vh, doh, lse, delta)

    # dK/dV: kv-major grid, q tiles innermost; outputs per QUERY head
    # (grouped heads sum outside), only the k/v inputs fold the group
    qspec2 = pl.BlockSpec((1, bq, dh), lambda hh, j, i, off: (hh, i, 0))
    kvspec2 = pl.BlockSpec((1, bkv, dh), lambda hh, j, i, off: (hh // G, j, 0))
    kvspec2_out = pl.BlockSpec((1, bkv, dh), lambda hh, j, i, off: (hh, j, 0))
    mlspec2 = pl.BlockSpec((1, bq, 1), lambda hh, j, i, off: (hh, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, block_q=bq, block_kv=bkv,
            masked=causal not in ("past", "none"), gated=causal != "none",
            window=window,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((h, skv, dh), f32),
            jax.ShapeDtypeStruct((h, skv, dh), f32),
        ],
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(h, skv // bkv, sq // bq),
            in_specs=[qspec2, kvspec2, kvspec2, qspec2, mlspec2, mlspec2],
            out_specs=[kvspec2_out, kvspec2_out],
            scratch_shapes=[
                pltpu.VMEM((bkv, dh), f32),
                pltpu.VMEM((bkv, dh), f32),
            ],
        ),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * h * sq * skv * dh // 2,
            bytes_accessed=(2 * sq + 2 * skv) * h * dh * q.dtype.itemsize,
            transcendentals=h * sq * skv,
        ),
        interpret=interpret,
    )(offsets, qh, kh, vh, doh, lse, delta)
    return (
        dq.transpose(1, 0, 2),
        _group_sum(dk),
        _group_sum(dv),
    )


# -- differentiable public API ------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, row_offset, scale, block_q, block_kv, interpret,
           causal=True, window=0):
    o, _ = _flash_forward(
        q, k, v, row_offset, scale, block_q, block_kv, interpret, causal,
        window,
    )
    return o


def _flash_fwd_rule(q, k, v, row_offset, scale, block_q, block_kv, interpret,
                    causal=True, window=0):
    o, lse = _flash_forward(
        q, k, v, row_offset, scale, block_q, block_kv, interpret, causal,
        window,
    )
    return o, (q, k, v, o, lse, row_offset)


def _flash_bwd_rule(scale, block_q, block_kv, interpret, causal, window,
                    res, do):
    q, k, v, o, lse, row_offset = res
    dq, dk, dv = flash_attention_bwd(
        q, k, v, o, lse, do,
        scale=scale, row_offset=row_offset, col_offset=0,
        block_q=block_q, block_kv=block_kv, interpret=interpret,
        causal="offset" if causal else "none", window=window,
    )
    d_off = np.zeros(np.shape(row_offset), jax.dtypes.float0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), d_off


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_s0(q, k, v, scale, block_q, block_kv, interpret, causal=True,
              window=0):
    """Static ``row_offset == 0`` variant: keeping the offset a python int
    through the custom_vjp lets BOTH directions take the triangular grid
    (a traced offset — the generic ``_flash`` — forces the rectangular
    masked grid, ~2x the live tiles)."""
    o, _ = _flash_forward(
        q, k, v, 0, scale, block_q, block_kv, interpret, causal, window
    )
    return o


def _flash_s0_fwd_rule(q, k, v, scale, block_q, block_kv, interpret,
                       causal=True, window=0):
    o, lse = _flash_forward(
        q, k, v, 0, scale, block_q, block_kv, interpret, causal, window
    )
    return o, (q, k, v, o, lse)


def _flash_s0_bwd_rule(scale, block_q, block_kv, interpret, causal, window,
                       res, do):
    q, k, v, o, lse = res
    dq, dk, dv = flash_attention_bwd(
        q, k, v, o, lse, do,
        scale=scale, row_offset=0, col_offset=0,
        block_q=block_q, block_kv=block_kv, interpret=interpret,
        causal="offset" if causal else "none", window=window,
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_s0.defvjp(_flash_s0_fwd_rule, _flash_s0_bwd_rule)


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "block_q", "block_kv", "interpret", "causal", "window"
    ),
)
def _flash_s0_jit(q, k, v, scale, block_q, block_kv, interpret, causal,
                  window):
    return _flash_s0(
        q, k, v, scale, block_q, block_kv, interpret, causal, window
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "block_q", "block_kv", "interpret", "causal", "window"
    ),
)
def _flash_dyn_jit(q, k, v, row_offset, scale, block_q, block_kv, interpret,
                   causal, window):
    return _flash(
        q, k, v, row_offset, scale, block_q, block_kv, interpret, causal,
        window,
    )


def flash_attention(
    q,
    k,
    v,
    *,
    scale: float,
    row_offset=0,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool = False,
    causal: bool = True,
    window: int = 0,
):
    """Flash attention — differentiable (custom_vjp flash backward).

    ``q``: [sq, h, dh] (global query rows start at ``row_offset``),
    ``k``/``v``: [skv, h_kv, dh] with ``h_kv | h`` — ``h_kv < h`` is GQA:
    query head ``hh`` attends kv head ``hh // (h/h_kv)`` (the kernels read
    the shared KV tile straight from the head index map; dK/dV return with
    ``h_kv`` heads). Returns [sq, h, dh]. ``sq % block_q == 0`` and
    ``skv % block_kv == 0`` (benchmark shapes are powers of two).

    ``causal=False`` is full bidirectional attention: every tile live,
    no mask, forward and backward. ``window > 0`` is sliding-window
    (local) attention: each query attends only the ``window`` most
    recent positions including itself — tiles entirely behind the band
    are skipped in forward AND backward (requires ``causal=True``;
    every row keeps at least its own key, so no row is ever empty).

    A literal ``row_offset=0`` (the full-sequence case: the flagship
    model's gathered attention, the cp ``flash`` impl at world=1, direct
    kernel calls) dispatches to the triangular grid — only live causal
    tiles are visited, in forward AND backward. A traced offset (ring /
    sharded callers) uses the rectangular masked grid, which any runtime
    mesh position can share.

    Block defaults swept on a real v5e at seq=8192, 8 heads x dh=128 bf16:
    (1024, 1024) reaches 124.5 TFLOPS with the triangular grid — 8.5x
    the einsum attention path, rising to 144 at seq=32768 (median-of-8
    device_loop windows, BASELINE.md round-2 protocol).
    """
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("window > 0 requires causal=True")
    if (
        window
        and isinstance(row_offset, (int, np.integer))
        and row_offset == 0
        and window >= max(q.shape[0], k.shape[0])
    ):
        # the band covers the whole causal triangle: identical math, but
        # window=0 dispatches to the triangular grid (~half the tiles)
        window = 0
    if isinstance(row_offset, (int, np.integer)) and row_offset == 0:
        return _flash_s0_jit(
            q, k, v, scale, block_q, block_kv, interpret, causal, window
        )
    return _flash_dyn_jit(
        q, k, v, jnp.asarray(row_offset, jnp.int32),
        scale, block_q, block_kv, interpret, causal, window,
    )


def ring_flash_attention(
    q,
    k,
    v,
    *,
    axis_name: str,
    axis_size: int,
    scale: float,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool = False,
    window: int = 0,
):
    """Context-parallel causal flash attention inside ``shard_map`` —
    differentiable end to end.

    ``q``/``k``/``v``: [s_loc, h, dh], the local sequence chunk of a
    sequence sharded over ``axis_name`` (size ``axis_size``). Forward: K/V
    chunks circulate the ring via ``ppermute`` while each device folds the
    arriving chunk into a carried flash accumulator (Liu et al. ring
    attention; the ``cp_ring_attention/ring_flash`` benchmark pattern).
    Backward (custom_vjp): per-chunk dQ accumulates locally; the dK/dV
    accumulators TRAVEL THE RING with their chunks, so after the last hop
    plus one delivery ``ppermute`` every gradient lands on its owner —
    the communication volume matches the forward's.

    GQA composes naturally: ``k``/``v`` may carry ``h_kv = h/G`` heads —
    the ring then ships the SMALL kv chunks (and their gradient
    accumulators), so context parallelism's wire bytes shrink by the
    same group factor as the serving cache.

    ``window > 0`` is sliding-window attention over the ring: chunks
    entirely behind the band are skipped — compute per device drops to
    the live hops, ~ceil(window / s_loc) + 1 of d (the ring traffic
    itself still circulates every chunk: the ppermute chain is the
    collective, and hop t's liveness differs per device).
    """
    _gqa_group(q, k)  # validates h % h_kv
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    return _ring_flash(
        q, k, v, axis_name, axis_size, scale, block_q, block_kv, interpret,
        window,
    )


def _ring_chunk_live(src, my, s_loc, window):
    """Is chunk ``src`` live for device ``my``'s queries? Causal upper
    edge: not entirely in the future. Window lower edge: its last key
    (src+1)*s_loc - 1 not entirely behind the band of the first query
    my*s_loc (the diagonal chunk is always live)."""
    live = src <= my
    if window:
        live = jnp.logical_and(
            live, (src + 1) * s_loc - 1 > my * s_loc - window
        )
    return live


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _ring_flash(
    q, k, v, axis_name, d, scale, block_q, block_kv, interpret, window
):
    o, _ = _ring_flash_forward(
        q, k, v, axis_name, d, scale, block_q, block_kv, interpret, window
    )
    return o


def _ring_flash_forward(
    q, k, v, axis_name, d, scale, block_q, block_kv, interpret, window
):
    my = jax.lax.axis_index(axis_name)
    s_loc, h, dh = q.shape
    fwd = [(i, (i + 1) % d) for i in range(d)]
    carry = init_flash_carry(s_loc, h, dh)
    k_cur, v_cur = k, v
    for t in range(d):
        src = (my - t) % d  # the chunk held after t hops came from src

        def fold(c, k_c=k_cur, v_c=v_cur, src_=src, t_=t):
            # t is STATIC: the t=0 chunk is exactly diagonal (equal
            # offsets), every later executed chunk strictly past — no
            # runtime-offset masking needed on either. A window needs
            # the mask on past chunks too (partially behind the band),
            # so those switch to the runtime-offset mode.
            if window:
                causal = "diagonal" if t_ == 0 else "offset"
            else:
                causal = "diagonal" if t_ == 0 else "past"
            return flash_attention_chunk(
                q, k_c, v_c, c,
                scale=scale,
                row_offset=my * s_loc,
                col_offset=src_ * s_loc,
                block_q=block_q,
                block_kv=block_kv,
                interpret=interpret,
                causal=causal,
                window=window,
            )

        # skip chunks entirely outside the live band (future, or — with
        # a window — entirely behind it)
        carry = jax.lax.cond(
            _ring_chunk_live(src, my, s_loc, window), fold, lambda c: c,
            carry,
        )
        if t + 1 < d:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm=fwd)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm=fwd)
    acc, m_run, l_run = carry
    o = finalize_flash_carry(carry, q.dtype)
    lse = jnp.where(l_run == 0.0, NEG_INF, m_run + jnp.log(l_run))
    return o, lse


def _ring_flash_fwd_rule(
    q, k, v, axis_name, d, scale, block_q, block_kv, interpret, window
):
    o, lse = _ring_flash_forward(
        q, k, v, axis_name, d, scale, block_q, block_kv, interpret, window
    )
    return o, (q, k, v, o, lse)


def _ring_flash_bwd_rule(
    axis_name, d, scale, block_q, block_kv, interpret, window, res, do
):
    q, k, v, o, lse = res
    my = jax.lax.axis_index(axis_name)
    s_loc = q.shape[0]
    fwd = [(i, (i + 1) % d) for i in range(d)]
    f32 = jnp.float32
    dq_acc = jnp.zeros(q.shape, f32)
    # the traveling gradient accumulators ride the ring WITH their chunks
    k_cur, v_cur = k, v
    dk_cur = jnp.zeros(k.shape, f32)
    dv_cur = jnp.zeros(v.shape, f32)
    for t in range(d):
        src = (my - t) % d

        def step(args, k_c=k_cur, v_c=v_cur, src_=src, t_=t):
            dq_a, dk_a, dv_a = args
            # the backward's windowed mode is offset-only (the bwd
            # kernels reject window elsewhere) — equal offsets make it
            # exact for the diagonal chunk too
            if window:
                causal = "offset"
            else:
                causal = "diagonal" if t_ == 0 else "past"
            dq_c, dk_c, dv_c = flash_attention_bwd(
                q, k_c, v_c, o, lse, do,
                scale=scale,
                row_offset=my * s_loc,
                col_offset=src_ * s_loc,
                block_q=block_q,
                block_kv=block_kv,
                interpret=interpret,
                causal=causal,
                window=window,
            )
            return dq_a + dq_c, dk_a + dk_c, dv_a + dv_c

        dq_acc, dk_cur, dv_cur = jax.lax.cond(
            _ring_chunk_live(src, my, s_loc, window), step, lambda a: a,
            (dq_acc, dk_cur, dv_cur),
        )
        if t + 1 < d:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm=fwd)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm=fwd)
            dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm=fwd)
            dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm=fwd)
    # after step d-1 the buffer on this device belongs to chunk my+1:
    # one delivery hop sends every accumulator home
    dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm=fwd)
    dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm=fwd)
    return (
        dq_acc.astype(q.dtype),
        dk_cur.astype(k.dtype),
        dv_cur.astype(v.dtype),
    )


_ring_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)
