"""Pallas flash attention (causal, forward): the attention compute engine.

The einsum attention paths materialize ``[h, q, kv]`` score matrices in
HBM, which caps them at memory bandwidth; this kernel keeps each
``[block_q, block_kv]`` score tile in VMEM with the standard
flash-attention online-softmax accumulator (running max / sum / output),
so the MXU stays fed. Used per-device: the context-parallel
implementations gather or ring the KV blocks and call this kernel on the
local query shard with the right global ``row_offset`` for the causal
mask.

No reference analogue (the reference has no attention operator,
SURVEY.md section 2.5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(
    off_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, block_q: int, block_kv: int,
):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    # runtime scalar (scalar-prefetch arg): the shard's first global query
    # row — one compiled kernel serves every mesh position
    row_offset = off_ref[0]

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # first global query row of this tile vs first key row of that tile:
    # skip tiles entirely in the future (the causal-half FLOP saving)
    q_start = row_offset + qi * block_q
    k_start = kj * block_kv

    @pl.when(q_start + block_q - 1 >= k_start)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_kv]
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        mask = (q_start + rows) >= (k_start + cols)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[:] = l_ref[:] * alpha + p.sum(-1, keepdims=True)
        m_ref[:] = m_new
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v_ref[0].astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == pl.num_programs(2) - 1)
    def _flush():
        o_ref[0] = (acc_ref[:] / l_ref[:]).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_q", "block_kv", "interpret"),
)
def flash_attention(
    q,
    k,
    v,
    *,
    scale: float,
    row_offset=0,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool = False,
):
    """Causal flash attention forward.

    ``q``: [sq, h, dh] (global query rows start at ``row_offset``),
    ``k``/``v``: [skv, h, dh]. Returns [sq, h, dh]. ``sq % block_q == 0``
    and ``skv % block_kv == 0`` (benchmark shapes are powers of two).

    Block defaults swept on a real v5e at seq=8192, 8 heads x dh=128 bf16:
    (1024, 1024) reaches ~174 TFLOPS — 12x the einsum attention path.
    """
    sq, h, dh = q.shape
    skv = k.shape[0]
    bq, bkv = min(block_q, sq), min(block_kv, skv)
    if sq % bq or skv % bkv:
        raise ValueError(
            f"(sq={sq}, skv={skv}) not divisible by blocks ({bq}, {bkv})"
        )
    qh = q.transpose(1, 0, 2)  # [h, sq, dh]
    kh = k.transpose(1, 0, 2)
    vh = v.transpose(1, 0, 2)
    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        block_q=bq,
        block_kv=bkv,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(h, sq // bq, skv // bkv),
        in_specs=[
            pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0)),
            pl.BlockSpec((1, bkv, dh), lambda hh, i, j, off: (hh, j, 0)),
            pl.BlockSpec((1, bkv, dh), lambda hh, i, j, off: (hh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, dh), lambda hh, i, j, off: (hh, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq, dh), jnp.float32),  # output accumulator
            pltpu.VMEM((bq, 1), jnp.float32),   # running max
            pltpu.VMEM((bq, 1), jnp.float32),   # running sum
        ],
    )
    offset = jnp.asarray(row_offset, jnp.int32).reshape(1)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((h, sq, dh), q.dtype),
        grid_spec=grid_spec,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * h * sq * skv * dh // 2,
            bytes_accessed=(2 * sq + 2 * skv) * h * dh * q.dtype.itemsize,
            transcendentals=h * sq * skv,
        ),
        interpret=interpret,
    )(offset, qh, kh, vh)
    return out.transpose(1, 0, 2)
