"""Hand-written Pallas TPU kernels (the framework's native-code layer)."""

from ddlb_tpu.ops.matmul import matmul  # noqa: F401
