"""Hand-written Pallas TPU kernels (the framework's native-code layer)."""

from ddlb_tpu.ops.matmul import matmul  # noqa: F401
from ddlb_tpu.ops.quantized_matmul import (  # noqa: F401
    int8_matmul,
    int8_matmul_pallas,
    quantization_atol,
    quantize_colwise,
    quantize_rowwise,
)
