"""Hand-written all-to-all expert-GEMM kernel (RDMA + MXU, one program).

The third collective shape done at the kernel level: after the ring
all-gather (`ring_ag_matmul`) and ring reduce-scatter (`ring_matmul_rs`)
of ops/collective_matmul.py, this kernel fuses the MoE exchange —
dispatch all-to-all, resident-expert GEMM, combine all-to-all — into ONE
Pallas program driving the ICI directly with
``pltpu.make_async_remote_copy`` (pallas_guide.md "Async Remote DMA").

Protocol (inside ``shard_map`` over a 1-D ``axis_name`` of d devices;
reference ambition mirrored: the nvFuser P2P overlap of
/root/reference/ddlb/primitives/TPColumnwise/fuser.py:102-146 applied to
the MoE pattern):

1. one global entry barrier (every peer must have entered before anyone
   RDMAs into anyone's landing buffers — the cross-invocation hazard
   gate, same role as the ring kernels' neighbor barrier);
2. ALL dispatch sends launch up front: group ``e`` of my tokens RDMAs
   into device ``e``'s landing slot ``[my]`` — slots are distinct per
   sender, so unlike the rings no credit gating is needed within a call;
3. expert GEMMs run in arrival order ``(my, my+1, …)``, each gated only
   by its own slot's recv semaphore — compute overlaps the still-flying
   dispatches;
4. each finished group's output RDMAs straight into the SOURCE device's
   output rows (``o_hbm[my*g :]`` addressed with MY index — receiver ``s``
   stores my result at its group ``my``), overlapping the combine with
   the next GEMM;
5. exit waits: all sends retired, all d-1 inbound output groups landed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddlb_tpu.ops.collective_matmul import _gemm_pipeline
from ddlb_tpu.ops.pallas_compat import CompilerParams


def _global_barrier(axis_name: str, d: int) -> None:
    """Block until EVERY peer reached this point (all-pairs signal)."""
    my = jax.lax.axis_index(axis_name)
    barrier = pltpu.get_barrier_semaphore()

    def signal(i, _):
        peer = jax.lax.rem(my + i, d)
        pltpu.semaphore_signal(
            barrier,
            inc=1,
            device_id=peer,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        return 0

    jax.lax.fori_loop(1, d, signal, 0)
    pltpu.semaphore_wait(barrier, d - 1)


def _a2a_matmul_kernel(
    a_hbm, w_hbm, disp_in, outb_in, o_hbm, disp_buf, out_buf,
    send_disp, recv_disp, send_out, recv_out, copy_sem, acc_ref,
    *, axis_name: str, d: int, bn: int, bk: int, interpret: bool = False,
):
    del disp_in, outb_in  # aliased landing/output buffers (HBM scratch
    # cannot be allocated by this toolchain)
    my = jax.lax.axis_index(axis_name)
    m_loc, k = a_hbm.shape
    g = m_loc // d
    nsteps = k // bk

    _global_barrier(axis_name, d)

    # 2) launch every dispatch: my group e -> device e's landing slot [my]
    def send_group(i, _):
        peer = jax.lax.rem(my + i, d)
        rdma = pltpu.make_async_remote_copy(
            src_ref=a_hbm.at[pl.ds(peer * g, g), :],
            dst_ref=disp_buf.at[my],
            send_sem=send_disp.at[peer],
            recv_sem=recv_disp.at[my],
            device_id=peer,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        return 0

    jax.lax.fori_loop(1, d, send_group, 0)
    # own group needs no wire: local copy into the landing slot
    cp = pltpu.make_async_copy(
        a_hbm.at[pl.ds(my * g, g), :], disp_buf.at[my], copy_sem
    )
    cp.start()
    cp.wait()

    # 3+4) GEMM each landed group, then fly its output home
    def step(t, _):
        s = jax.lax.rem(my + t, d)  # source whose tokens we process

        @pl.when(t > 0)
        def _arrived():
            # the landing slot for source s carries its own recv credit
            pltpu.make_async_copy(
                disp_buf.at[s], disp_buf.at[s], recv_disp.at[s]
            ).wait()

        _gemm_pipeline(
            disp_buf.at[s],
            w_hbm,
            out_buf.at[s],
            nsteps=nsteps,
            bn=bn,
            bk=bk,
            acc_ref=acc_ref,
            interpret=interpret,
        )

        @pl.when(t > 0)
        def _combine_remote():
            # receiver s stores MY expert's output at ITS group index my;
            # the recv credit is indexed by the SOURCE (my) so each
            # arriving group lands on its own semaphore slot
            rdma = pltpu.make_async_remote_copy(
                src_ref=out_buf.at[s],
                dst_ref=o_hbm.at[pl.ds(my * g, g), :],
                send_sem=send_out.at[s],
                recv_sem=recv_out.at[my],
                device_id=s,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()

        @pl.when(t == 0)
        def _combine_local():
            # started here, retired in the exit drain — a synchronous
            # wait would stall the step-1 GEMM behind a g*n HBM copy
            pltpu.make_async_copy(
                out_buf.at[s], o_hbm.at[pl.ds(my * g, g), :], copy_sem
            ).start()

        return 0

    jax.lax.fori_loop(0, d, step, 0)
    # retire the local combine copy launched at step 0
    pltpu.make_async_copy(
        out_buf.at[my], o_hbm.at[pl.ds(my * g, g), :], copy_sem
    ).wait()

    # 5) retire everything before leaving: our outbound sends and the
    # d-1 output groups other experts RDMA'd into our o_hbm
    def drain(i, _):
        peer = jax.lax.rem(my + i, d)
        pltpu.make_async_copy(
            a_hbm.at[pl.ds(peer * g, g), :],
            a_hbm.at[pl.ds(peer * g, g), :],
            send_disp.at[peer],
        ).wait()
        pltpu.make_async_copy(
            out_buf.at[peer], out_buf.at[peer], send_out.at[peer]
        ).wait()
        pltpu.make_async_copy(
            o_hbm.at[pl.ds(peer * g, g), :],
            o_hbm.at[pl.ds(peer * g, g), :],
            recv_out.at[peer],
        ).wait()
        return 0

    jax.lax.fori_loop(1, d, drain, 0)


def alltoall_expert_matmul(
    a_shard,
    w_expert,
    *,
    axis_name: str = "tp",
    axis_size: int,
    block_n: int = 512,
    block_k: int = 512,
    interpret: bool = False,
    collective_id: int = 3,
):
    """Fused MoE dispatch/expert-GEMM/combine with kernel-level RDMA.

    Call inside ``shard_map``: ``a_shard [m/d, k]`` (d contiguous routing
    groups of g = m/d^2 tokens), ``w_expert [k, n]`` (the resident
    expert) -> ``[m/d, n]`` in token order — the ep_alltoall contract
    (primitives/ep_alltoall/base.py).
    """
    d = axis_size
    m_loc, k = a_shard.shape
    n = w_expert.shape[1]
    if m_loc % d:
        raise ValueError(f"m/d={m_loc} not divisible by d={d}")
    g = m_loc // d
    bn, bk = min(block_n, n), min(block_k, k)
    if n % bn or k % bk:
        raise ValueError(f"(n={n}, k={k}) not divisible by ({bn}, {bk})")
    space = pltpu.VMEM if interpret else pltpu.ANY
    kernel = functools.partial(
        _a2a_matmul_kernel, axis_name=axis_name, d=d, bn=bn, bk=bk,
        interpret=bool(interpret),
    )
    disp_init = jnp.zeros((d, g, k), a_shard.dtype)
    outb_init = jnp.zeros((d, g, n), a_shard.dtype)
    out, _, _ = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((m_loc, n), a_shard.dtype),
            jax.ShapeDtypeStruct((d, g, k), a_shard.dtype),
            jax.ShapeDtypeStruct((d, g, n), a_shard.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
        ),
        # landing and output buffers ride as inputs 2/3 aliased to
        # outputs 1/2
        input_output_aliases={2: 1, 3: 2},
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((d,)),            # dispatch sends
            pltpu.SemaphoreType.DMA((d,)),            # dispatch recvs
            pltpu.SemaphoreType.DMA((d,)),            # combine sends
            pltpu.SemaphoreType.DMA((d,)),            # combine recvs
            pltpu.SemaphoreType.DMA,                  # local copies
            pltpu.VMEM((g, bn), jnp.float32),         # GEMM accumulator
        ],
        compiler_params=CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interpret,
    )(a_shard, w_expert, disp_init, outb_init)
    return out
