"""Hand-written ring collective-matmul kernels (RDMA + MXU in one program).

This is the kernel-level re-creation of the reference's nvFuser P2P
pipelines (/root/reference/ddlb/primitives/TPColumnwise/fuser.py:102-146,
TPRowwise/fuser.py:116-169): where nvFuser overlaps NCCL/symmetric-memory
P2P copies with GEMM chunks on CUDA streams, these kernels drive the ICI
directly with ``pltpu.make_async_remote_copy`` while the MXU computes the
chunk currently held — communication and compute overlap inside ONE Pallas
program, no XLA scheduler involved (pallas_guide.md "Patterns: Ring
Collectives" + "Async Remote DMA").

Layout (inside ``shard_map`` over a 1-D ``axis_name`` ring of d devices):

- ``ring_ag_matmul``: A row-shard ``[m/d, k]`` circulates clockwise through
  a double-buffered HBM scratch; at step t a device holds chunk
  ``(my - t) % d``, GEMMs it into the matching output rows via an inner
  ``emit_pipeline`` (HBM->VMEM tile pipeline), and has already launched the
  RDMA forwarding it — the AG+GEMM overlap.
- ``ring_matmul_rs``: partial-sum accumulators circulate instead: at step t
  a device GEMMs the A rows of chunk ``(my + d - 1 - t) % d`` and adds them
  into the accumulator just received, then forwards it; after d steps each
  device holds its own fully-reduced output chunk — the GEMM+RS overlap.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddlb_tpu.ops.pallas_compat import CompilerParams


def _neighbor_barrier(axis_name: str, d: int) -> None:
    """Block until both ring neighbors reached this point
    (pallas_guide.md "Local Barrier Between Neighbors")."""
    my = jax.lax.axis_index(axis_name)
    barrier = pltpu.get_barrier_semaphore()
    for nb in ((my - 1) % d, (my + 1) % d):
        pltpu.semaphore_signal(
            barrier,
            inc=1,
            device_id=nb,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
    pltpu.semaphore_wait(barrier, 2)


def _gemm_pipeline(a_hbm, b_hbm, o_hbm, *, nsteps, bn, bk, acc_ref,
                   interpret=False):
    """Inner tiled GEMM ``o = a @ b`` between HBM refs with a VMEM f32
    accumulator; grid is (n-tiles, k-tiles), k innermost."""
    m_loc = a_hbm.shape[0]

    if interpret:
        # emit_pipeline needs a real TPU generation; the interpreter can
        # read refs wholesale, so compute directly.
        o_hbm[...] = jnp.dot(
            a_hbm[...], b_hbm[...], preferred_element_type=jnp.float32
        ).astype(o_hbm.dtype)
        return

    def inner(a_ref, b_ref, o_ref):
        s = pl.program_id(1)

        @pl.when(s == 0)
        def _zero():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        acc_ref[:] += jnp.dot(
            a_ref[:], b_ref[:], preferred_element_type=jnp.float32
        )

        @pl.when(s == nsteps - 1)
        def _flush():
            o_ref[:] = acc_ref[:].astype(o_ref.dtype)

    pltpu.emit_pipeline(
        inner,
        grid=(o_hbm.shape[1] // bn, nsteps),
        in_specs=[
            pl.BlockSpec((m_loc, bk), lambda j, s: (0, s)),
            pl.BlockSpec((bk, bn), lambda j, s: (s, j)),
        ],
        out_specs=[pl.BlockSpec((m_loc, bn), lambda j, s: (0, j))],
    )(a_hbm, b_hbm, o_hbm)


# ---------------------------------------------------------------------------
# AG + GEMM ring
# ---------------------------------------------------------------------------


def _ag_matmul_kernel(
    a_hbm, b_hbm, buf_in, o_hbm, comm_buf, send_sem, recv_sem, copy_sem,
    credit_sem, acc_ref,
    *, axis_name: str, d: int, bn: int, bk: int, interpret: bool = False,
):
    del buf_in  # aliased with comm_buf (scratch in HBM cannot be allocated
    # by this toolchain, so the ring buffer is an input/output-aliased pair)
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, d)
    m_loc, k = a_hbm.shape
    nsteps = k // bk

    # seed slot 0 with the local shard, then make sure every neighbor's
    # buffer is seeded before anyone RDMAs into it
    cp = pltpu.make_async_copy(a_hbm, comm_buf.at[0], copy_sem)
    cp.start()
    cp.wait()
    _neighbor_barrier(axis_name, d)

    left = jax.lax.rem(my - 1 + d, d)

    def step(t, _):
        slot = jax.lax.rem(t, 2)
        nxt = jax.lax.rem(t + 1, 2)

        @pl.when(t < d - 1)
        def _send():
            # Buffer-reuse hazard: our comm_buf[nxt] is the target of this
            # send on the RIGHT neighbor; it may still be reading it for its
            # own step t-1 send. A credit from the right neighbor certifies
            # the target slot is free (first two sends hit fresh buffers).
            @pl.when(t >= 1)
            def _credit_gate():
                pltpu.semaphore_wait(credit_sem, 1)

            # forward the chunk we hold while we GEMM it below
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[slot],
                dst_ref=comm_buf.at[nxt],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()

        chunk = jax.lax.rem(my - t + d, d)
        _gemm_pipeline(
            comm_buf.at[slot],
            b_hbm,
            o_hbm.at[pl.ds(chunk * m_loc, m_loc), :],
            nsteps=nsteps,
            bn=bn,
            bk=bk,
            acc_ref=acc_ref,
            interpret=interpret,
        )

        @pl.when(t < d - 1)
        def _wait():
            # next chunk arrived; once our outgoing send has fully read
            # comm_buf[slot], tell the left neighbor the slot is free
            pltpu.make_async_copy(
                comm_buf.at[nxt], comm_buf.at[nxt], recv_sem.at[nxt]
            ).wait()
            pltpu.make_async_copy(
                comm_buf.at[slot], comm_buf.at[slot], send_sem.at[slot]
            ).wait()
            pltpu.semaphore_signal(
                credit_sem,
                inc=1,
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        return 0

    jax.lax.fori_loop(0, d, step, 0)
    if d >= 2:
        # one credit is produced but never consumed (the last send needs no
        # gate); drain it so the semaphore exits clean
        pltpu.semaphore_wait(credit_sem, 1)


def ring_ag_matmul(
    a_shard,
    b,
    *,
    axis_name: str = "tp",
    axis_size: int,
    block_n: int = 512,
    block_k: int = 512,
    interpret: bool = False,
    collective_id: int = 1,
):
    """All-gather + GEMM with kernel-level RDMA/compute overlap.

    Call inside ``shard_map``: ``a_shard [m/d, k]``, ``b [k, n]`` ->
    ``[m, n]`` (the full product, like order=AG_before).
    """
    m_loc, k = a_shard.shape
    n = b.shape[1]
    bn, bk = min(block_n, n), min(block_k, k)
    if n % bn or k % bk:
        raise ValueError(f"(n={n}, k={k}) not divisible by ({bn}, {bk})")
    # interpret mode cannot reference ANY/HBM directly nor allocate
    # ANY-space scratch; its VMEM is unbounded, so everything parks in VMEM
    # when emulating
    space = pltpu.VMEM if interpret else pltpu.ANY
    kernel = functools.partial(
        _ag_matmul_kernel, axis_name=axis_name, d=axis_size, bn=bn, bk=bk,
        interpret=bool(interpret),
    )
    buf_init = jnp.zeros((2, m_loc, k), a_shard.dtype)
    out, _ = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((m_loc * axis_size, n), a_shard.dtype),
            jax.ShapeDtypeStruct((2, m_loc, k), a_shard.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
        ),
        # ring double buffer rides as input 2 aliased to output 1
        input_output_aliases={2: 1},
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),            # send
            pltpu.SemaphoreType.DMA((2,)),            # recv
            pltpu.SemaphoreType.DMA,                  # local seed copy
            pltpu.SemaphoreType.REGULAR,              # buffer-free credits
            pltpu.VMEM((m_loc, bn), jnp.float32),     # GEMM accumulator
        ],
        compiler_params=CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interpret,
    )(a_shard, b, buf_init)
    return out


# ---------------------------------------------------------------------------
# GEMM + reduce-scatter ring
# ---------------------------------------------------------------------------


def _matmul_rs_kernel(
    a_hbm, b_hbm, acc_in, part_in, o_hbm, acc_buf, partial_buf, send_sem,
    recv_sem, copy_sem, credit_sem, acc_ref,
    *, axis_name: str, d: int, bn: int, bk: int, interpret: bool = False,
):
    del acc_in, part_in  # aliased with acc_buf / partial_buf (HBM scratch
    # cannot be allocated by this toolchain)
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, d)
    left = jax.lax.rem(my - 1 + d, d)
    m, kd = a_hbm.shape
    m_loc = m // d
    nsteps = kd // bk
    n = o_hbm.shape[1]

    _neighbor_barrier(axis_name, d)

    def step(t, _):
        slot = jax.lax.rem(t, 2)
        nxt = jax.lax.rem(t + 1, 2)
        # chunk schedule: after d steps each device's accumulator holds its
        # own chunk, fully reduced (same schedule as the shard_map ring in
        # primitives/tp_rowwise/overlap.py)
        chunk = jax.lax.rem(my + d - 1 - t, d)

        # 1. partial = A[chunk rows] @ B — overlaps the inbound acc RDMA
        #    and our still-in-flight send from step t-1
        _gemm_pipeline(
            a_hbm.at[pl.ds(chunk * m_loc, m_loc), :],
            b_hbm,
            partial_buf,
            nsteps=nsteps,
            bn=bn,
            bk=bk,
            acc_ref=acc_ref,
            interpret=interpret,
        )

        # 2. retire the previous send (it read acc_buf[nxt]) and tell the
        #    left neighbor that buffer may be overwritten
        @pl.when(t >= 1)
        def _retire():
            pltpu.make_async_copy(
                acc_buf.at[nxt], acc_buf.at[nxt], send_sem.at[nxt]
            ).wait()
            pltpu.semaphore_signal(
                credit_sem,
                inc=1,
                device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        # 3. the travelling accumulator for this step has landed in
        #    acc_buf[slot]
        @pl.when(t >= 1)
        def _recv():
            pltpu.make_async_copy(
                acc_buf.at[slot], acc_buf.at[slot], recv_sem.at[slot]
            ).wait()

        # 4. fold the partial into it (first step initializes)
        if interpret:
            acc_buf[slot] = jnp.where(
                t == 0, partial_buf[...], partial_buf[...] + acc_buf[slot]
            )
        else:

            def add_body(p_ref, a_in_ref, o_ref):
                @pl.when(t == 0)
                def _init():
                    o_ref[:] = p_ref[:]

                @pl.when(t > 0)
                def _add():
                    o_ref[:] = p_ref[:] + a_in_ref[:]

            pltpu.emit_pipeline(
                add_body,
                grid=(n // bn,),
                in_specs=[
                    pl.BlockSpec((m_loc, bn), lambda j: (0, j)),
                    pl.BlockSpec((m_loc, bn), lambda j: (0, j)),
                ],
                out_specs=[pl.BlockSpec((m_loc, bn), lambda j: (0, j))],
            )(partial_buf, acc_buf.at[slot], acc_buf.at[slot])

        # 5. forward the partial sums; the next iteration's GEMM overlaps
        #    this transfer
        @pl.when(t < d - 1)
        def _send():
            @pl.when(t >= 1)
            def _credit_gate():
                pltpu.semaphore_wait(credit_sem, 1)

            rdma = pltpu.make_async_remote_copy(
                src_ref=acc_buf.at[slot],
                dst_ref=acc_buf.at[nxt],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()

        # 6. last step: the accumulator is this device's finished chunk
        @pl.when(t == d - 1)
        def _flush():
            cp = pltpu.make_async_copy(acc_buf.at[slot], o_hbm, copy_sem)
            cp.start()
            cp.wait()

        return 0

    jax.lax.fori_loop(0, d, step, 0)
    if d >= 2:
        pltpu.semaphore_wait(credit_sem, 1)


def ring_matmul_rs(
    a_shard,
    b_shard,
    *,
    axis_name: str = "tp",
    axis_size: int,
    block_n: int = 512,
    block_k: int = 512,
    interpret: bool = False,
    collective_id: int = 2,
):
    """GEMM + reduce-scatter with kernel-level RDMA/compute overlap.

    Call inside ``shard_map``: ``a_shard [m, k/d]``, ``b_shard [k/d, n]`` ->
    ``[m/d, n]`` (this device's fully-reduced output rows).
    """
    m, kd = a_shard.shape
    n = b_shard.shape[1]
    if m % axis_size:
        raise ValueError(f"m={m} not divisible by axis_size={axis_size}")
    m_loc = m // axis_size
    bn, bk = min(block_n, n), min(block_k, kd)
    if n % bn or kd % bk:
        raise ValueError(f"(n={n}, k/d={kd}) not divisible by ({bn}, {bk})")
    space = pltpu.VMEM if interpret else pltpu.ANY
    kernel = functools.partial(
        _matmul_rs_kernel, axis_name=axis_name, d=axis_size, bn=bn, bk=bk,
        interpret=bool(interpret),
    )
    acc_init = jnp.zeros((2, m_loc, n), a_shard.dtype)
    part_init = jnp.zeros((m_loc, n), a_shard.dtype)
    out, _, _ = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((m_loc, n), a_shard.dtype),
            jax.ShapeDtypeStruct((2, m_loc, n), a_shard.dtype),
            jax.ShapeDtypeStruct((m_loc, n), a_shard.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
            pl.BlockSpec(memory_space=space),
        ),
        # travelling accumulators and the partial-product buffer ride as
        # inputs 2/3 aliased to outputs 1/2
        input_output_aliases={2: 1, 3: 2},
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),            # send
            pltpu.SemaphoreType.DMA((2,)),            # recv
            pltpu.SemaphoreType.DMA,                  # output flush copy
            pltpu.SemaphoreType.REGULAR,              # buffer-free credits
            pltpu.VMEM((m_loc, bn), jnp.float32),     # GEMM accumulator
        ],
        compiler_params=CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interpret,
    )(a_shard, b_shard, acc_init, part_init)
    return out
