"""Pallas fused decode attention: one query token vs the KV cache.

The einsum decode path materializes the ``[b, h_kv, G, 1, S]`` score
tensor in HBM between the score einsum, the softmax and the value
einsum. At long contexts that round-trip is pure overhead on a step
whose whole cost is HBM bytes — and it GROWS as the fast-decode levers
shrink the cache (at int8+GQA the score tensor can approach a quarter of
the traffic). This kernel streams the cache once: S-tiles of K and V are
read tile-by-tile (ALL kv heads per tile, so the DMA is contiguous in
the cache's native ``[b, S, h_kv, dh]`` layout), scores live in VMEM,
and the classic online-softmax recurrence (m, l, acc) folds tiles as
they arrive. int8 caches are dequantized IN the kernel — the HBM read is
genuinely the int8 payload + scales, never a dequantized copy.

Semantics match ``models/decode._cache_attend`` exactly: positions
``<= pos[b]`` are live (per-sequence ragged positions are the native
form; scalar callers broadcast), ``window > 0`` drops positions behind
the sliding window, and int8 dequantization rounds through the model
dtype (``_cache_read``'s contract) so the two paths agree to float
tolerance. Grouped queries share their kv head inside the kernel via a
reshape — no head replication.

No reference analogue (the reference has no attention operator,
SURVEY.md section 2.5).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ddlb_tpu.ops.pallas_compat import CompilerParams

NEG_INF = -1e30


def _pick_block(S: int, want: int) -> int:
    """Largest divisor of ``S`` that is ``<= want`` (TPU pallas wants
    whole tiles; caches sized to powers of two hit ``want`` itself)."""
    b = min(want, S)
    while S % b:
        b -= 1
    return b


def _attn_tile_body(
    q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref,
    *, sj, pos, s_start, live_tile, block: int, h_kv: int, G: int,
    dh: int, scale: float, window: int, int8: bool, dtype,
):
    """The ONE online-softmax recurrence (init / masked tile update /
    flush) shared by the contiguous and paged kernels — they differ only
    in how a grid step finds its KV tile (sequential block vs
    table-mapped page) and in the extra liveness term the paged form
    adds; the numerically delicate part lives here once."""

    @pl.when(sj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(live_tile)
    def _update():
        # [block, h_kv, dh] cache tiles, contiguous in the native
        # layout; dequantize through the model dtype (the _cache_read
        # contract) so einsum/kernel numerics agree
        k = k_ref[0]
        v = v_ref[0]
        if int8:
            k = (k.astype(jnp.float32) * ks_ref[0]).astype(dtype)
            v = (v.astype(jnp.float32) * vs_ref[0]).astype(dtype)
        kh = k.astype(jnp.float32).transpose(1, 0, 2)   # [h_kv, bs, dh]
        vh = v.astype(jnp.float32).transpose(1, 0, 2)
        q = q_ref[0].astype(jnp.float32).reshape(h_kv, G, dh) * scale
        # s[h_kv, G, bs]: grouped queries against their shared kv head
        s = jax.lax.dot_general(
            q, kh, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        cols = s_start + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, block), 2
        )
        live = cols <= pos
        if window:
            live &= cols > pos - window
        s = jnp.where(live, s, NEG_INF)

        m_prev, l_prev, acc_prev = m_ref[:], l_ref[:], acc_ref[:]
        m_new = jnp.maximum(m_prev, s.max(-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # a fully-masked tile row must contribute zero mass, not
        # exp(NEG_INF - NEG_INF) = 1 per column
        p = jnp.where(live, p, 0.0)
        l_ref[:] = l_prev * alpha + p.sum(-1, keepdims=True)
        acc_ref[:] = acc_prev * alpha + jax.lax.dot_general(
            p, vh, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(sj == pl.num_programs(1) - 1)
    def _flush():
        l = l_ref[:]
        out = acc_ref[:] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = out.reshape(h_kv * G, dh).astype(o_ref.dtype)


def _decode_attn_kernel(
    pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
    m_ref, l_ref, acc_ref,
    *, block_s: int, h_kv: int, G: int, dh: int, scale: float,
    window: int, int8: bool, dtype,
):
    bi = pl.program_id(0)
    sj = pl.program_id(1)
    pos = pos_ref[bi]
    s_start = sj * block_s

    # tile skip: not entirely in the future, and (static window) not
    # entirely behind the sliding window — windowed decode then costs
    # O(window) live tiles, not O(S)
    live_tile = s_start <= pos
    if window:
        live_tile = jnp.logical_and(
            live_tile, s_start + block_s > pos - window
        )

    _attn_tile_body(
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref,
        sj=sj, pos=pos, s_start=s_start, live_tile=live_tile,
        block=block_s, h_kv=h_kv, G=G, dh=dh, scale=scale, window=window,
        int8=int8, dtype=dtype,
    )


@functools.partial(
    jax.jit,
    static_argnames=("window", "block_s", "interpret"),
)
def decode_attention(
    q,
    k_cache,
    v_cache,
    pos,
    *,
    k_scale=None,
    v_scale=None,
    window: int = 0,
    block_s: int = 512,
    interpret=False,
):
    """Fused single-token cache attention.

    ``q``: [b, h, dh]; ``k_cache``/``v_cache``: [b, S, h_kv, dh] (the
    cache's native layout; int8 with ``k_scale``/``v_scale``
    [b, S, h_kv, 1] f32, or the model dtype with scales None);
    ``pos``: [b] int32 per-sequence live positions (scalar broadcasts).
    Returns [b, h, dh] in the query dtype.
    """
    b, h, dh = q.shape
    _, S, h_kv, _ = k_cache.shape
    if h % h_kv:
        raise ValueError(f"h={h} not divisible by h_kv={h_kv}")
    G = h // h_kv
    int8 = k_cache.dtype == jnp.int8
    if int8 and (k_scale is None or v_scale is None):
        raise ValueError("int8 cache needs k_scale and v_scale")
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    bs = _pick_block(S, block_s)

    kernel = functools.partial(
        _decode_attn_kernel,
        block_s=bs, h_kv=h_kv, G=G, dh=dh,
        scale=1.0 / float(np.sqrt(dh)), window=int(window), int8=int8,
        dtype=q.dtype,
    )
    qspec = pl.BlockSpec((1, h, dh), lambda bi, sj, pos_p: (bi, 0, 0))
    kvspec = pl.BlockSpec(
        (1, bs, h_kv, dh), lambda bi, sj, pos_p: (bi, sj, 0, 0)
    )
    ospec = pl.BlockSpec((1, h, dh), lambda bi, sj, pos_p: (bi, 0, 0))
    if int8:
        sspec = pl.BlockSpec(
            (1, bs, h_kv, 1), lambda bi, sj, pos_p: (bi, sj, 0, 0)
        )
        in_specs = [qspec, kvspec, kvspec, sspec, sspec]
        operands = (q, k_cache, v_cache, k_scale, v_scale)
    else:
        # scale slots unused: ONE tiny constant-index block per grid
        # step (not an S-proportional dummy stream) keeps a single
        # kernel signature for both cache precisions at ~zero traffic
        sspec = pl.BlockSpec(
            (1, 1, h_kv, 1), lambda bi, sj, pos_p: (0, 0, 0, 0)
        )
        dummy = jnp.zeros((1, 1, h_kv, 1), jnp.float32)
        in_specs = [qspec, kvspec, kvspec, sspec, sspec]
        operands = (q, k_cache, v_cache, dummy, dummy)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, S // bs),
        in_specs=in_specs,
        out_specs=ospec,
        scratch_shapes=[
            pltpu.VMEM((h_kv, G, 1), jnp.float32),
            pltpu.VMEM((h_kv, G, 1), jnp.float32),
            pltpu.VMEM((h_kv, G, dh), jnp.float32),
        ],
    )
    itemsize = k_cache.dtype.itemsize
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, dh), q.dtype),
        grid_spec=grid_spec,
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * S * dh,
            bytes_accessed=2 * b * S * h_kv * dh * itemsize
            + (2 * b * S * h_kv * 4 if int8 else 0)
            + 2 * b * h * dh * q.dtype.itemsize,
            transcendentals=b * h * S,
        ),
        interpret=interpret,
    )(pos, *operands)


def _paged_decode_attn_kernel(
    pos_ref, table_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
    m_ref, l_ref, acc_ref,
    *, page_size: int, num_pages: int, h_kv: int, G: int, dh: int,
    scale: float, window: int, int8: bool, dtype,
):
    """``_attn_tile_body`` with the KV tile for grid step ``sj`` fetched
    from the PAGE the slot's table maps — the block index map does the
    lookup (see ``paged_decode_attention``); this wrapper only adds the
    "is this table entry mapped" predicate to tile liveness."""
    bi = pl.program_id(0)
    sj = pl.program_id(1)
    pos = pos_ref[bi]
    s_start = sj * page_size

    live_tile = jnp.logical_and(
        s_start <= pos,
        # sentinel (unmapped) pages contribute nothing — the paged form
        # of the contiguous layout's zero-filled tail
        table_ref[bi, sj] < num_pages,
    )
    if window:
        live_tile = jnp.logical_and(
            live_tile, s_start + page_size > pos - window
        )

    _attn_tile_body(
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref,
        sj=sj, pos=pos, s_start=s_start, live_tile=live_tile,
        block=page_size, h_kv=h_kv, G=G, dh=dh, scale=scale,
        window=window, int8=int8, dtype=dtype,
    )


@functools.partial(
    jax.jit,
    static_argnames=("window", "interpret"),
)
def paged_decode_attention(
    q,
    k_pool,
    v_pool,
    table,
    pos,
    *,
    k_scale=None,
    v_scale=None,
    window: int = 0,
    interpret=False,
):
    """Fused single-token cache attention over a PAGED cache.

    ``q``: [b, h, dh]; ``k_pool``/``v_pool``: [P, page_size, h_kv, dh]
    (the shared page pool, int8 with [P, page_size, h_kv, 1] f32 scale
    pools); ``table``: [b, max_pages] int32 page ids (the sentinel id P
    marks unmapped entries); ``pos``: [b] int32 live positions.

    The page table rides as a prefetched scalar operand and the KV block
    index map READS it: grid step (bi, sj) fetches page
    ``table[bi, sj]`` — so only mapped pages ever stream from HBM
    (sentinel entries clamp their fetch to page P-1 and are masked dead
    in the kernel; the pipeline still pays that one redundant page read
    per unmapped tail entry, the static-shape tax). The einsum paged path
    instead gathers the whole linear view through HBM first —
    this kernel IS that gather, fused into the attention.
    """
    b, h, dh = q.shape
    P, ps, h_kv, _ = k_pool.shape
    if h % h_kv:
        raise ValueError(f"h={h} not divisible by h_kv={h_kv}")
    G = h // h_kv
    int8 = k_pool.dtype == jnp.int8
    if int8 and (k_scale is None or v_scale is None):
        raise ValueError("int8 cache needs k_scale and v_scale")
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    table = jnp.asarray(table, jnp.int32)
    max_pages = table.shape[1]

    kernel = functools.partial(
        _paged_decode_attn_kernel,
        page_size=ps, num_pages=P, h_kv=h_kv, G=G, dh=dh,
        scale=1.0 / float(np.sqrt(dh)), window=int(window), int8=int8,
        dtype=q.dtype,
    )

    def page_of(bi, sj, pos_p, table_p):
        del pos_p
        return (jnp.minimum(table_p[bi, sj], P - 1), 0, 0, 0)

    qspec = pl.BlockSpec((1, h, dh), lambda bi, sj, pos_p, tab_p: (bi, 0, 0))
    kvspec = pl.BlockSpec((1, ps, h_kv, dh), page_of)
    ospec = pl.BlockSpec((1, h, dh), lambda bi, sj, pos_p, tab_p: (bi, 0, 0))
    if int8:
        sspec = pl.BlockSpec((1, ps, h_kv, 1), page_of)
        operands = (q, k_pool, v_pool, k_scale, v_scale)
    else:
        sspec = pl.BlockSpec(
            (1, 1, h_kv, 1), lambda bi, sj, pos_p, tab_p: (0, 0, 0, 0)
        )
        dummy = jnp.zeros((1, 1, h_kv, 1), jnp.float32)
        operands = (q, k_pool, v_pool, dummy, dummy)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_pages),
        in_specs=[qspec, kvspec, kvspec, sspec, sspec],
        out_specs=ospec,
        scratch_shapes=[
            pltpu.VMEM((h_kv, G, 1), jnp.float32),
            pltpu.VMEM((h_kv, G, 1), jnp.float32),
            pltpu.VMEM((h_kv, G, dh), jnp.float32),
        ],
    )
    itemsize = k_pool.dtype.itemsize
    S = max_pages * ps
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, dh), q.dtype),
        grid_spec=grid_spec,
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * S * dh,
            bytes_accessed=2 * b * S * h_kv * dh * itemsize
            + (2 * b * S * h_kv * 4 if int8 else 0)
            + 2 * b * h * dh * q.dtype.itemsize,
            transcendentals=b * h * S,
        ),
        interpret=interpret,
    )(pos, table, *operands)
