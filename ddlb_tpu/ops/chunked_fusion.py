"""Shared chunked compute/collective fusion engine (T3-style pipelining).

One ring scheduler behind every family's overlap member (ISSUE 10,
generalizing the fixed-granularity rings of ``ops/collective_matmul.py``
and ``ops/ring_collectives.py``): the GEMM is tiled along the sharded
dimension into a configurable ``chunk_count`` pieces, each chunk's
collective phase is an explicit ``ppermute`` ring, and chunk ``j+1``'s
ring hops carry no data dependency on chunk ``j``'s matmul — XLA's
async collectives + latency-hiding scheduler therefore overlap them,
which is exactly the T3 (arxiv 2401.16677) / fused
computation-collective (arxiv 2305.06942) schedule expressed in XLA's
compilation model instead of CUDA streams.

Double buffering: at steady state exactly two chunk buffers are live —
the chunk being consumed by the MXU and the chunk in flight on the ring
(rotating ``ppermute`` buffers in this shard_map path; the Pallas path
holds the same two slots VMEM/HBM-resident, see ``pallas`` below).

Schedule model (mirrored by ``perfmodel.cost``'s chunk-granularity
term): with per-call compute floor ``C`` and wire floor ``W`` split
into ``c`` chunks, the pipeline runs ``max(C, W) + min(C, W)/c`` — the
fill/drain of one chunk's hidden phase is the part perfect overlap
cannot remove. ``c=1`` degenerates to the sequential schedule
``C + W``; ``c → ∞`` approaches the ideal ``max(C, W)``.

Wire invariant (DDLB123): chunking must not change the total wire,
only the schedule. Every builder here moves exactly the family's
closed-form ring bytes — AG ``shard*(d-1)``, RS ``(S/d)*(d-1)``, AR
``2*(S/d)*(d-1)``, A2A ``(shard/d)*(d-1)`` — because each chunk's ring
moves ``1/c`` of the unchunked payload and there are ``c`` chunks; the
semantic SPMD analyzer verifies this per member against
``wire_bytes()``.

Four builders, one per family overlap member:

- ``build_chunked_ag_matmul``     — tp_columnwise: per-chunk ring AG,
  then the chunk's GEMM (comm leads, compute drains);
- ``build_chunked_matmul_rs``     — tp_rowwise: per-chunk partial GEMM,
  then the chunk's ring RS (compute leads, comm drains);
- ``build_chunked_matmul_ar``     — dp_allreduce: the gradient AR
  decomposed RS→AG around each chunk's grad GEMM;
- ``build_chunked_alltoall_expert`` — ep_alltoall: per-expert chunk
  dispatch/combine exchanges around each chunk's expert GEMM.

Pallas path: the VMEM-resident specialization of this engine is the
hand-written RDMA kernel pair in ``ops/collective_matmul.py`` — their
two comm-buffer slots are this module's rotating buffers held on-chip,
with the ring granularity pinned to ``chunk_count == axis_size`` (one
chunk per ring step, the only granularity the kernels' semaphore
protocol encodes). ``build_chunked_ag_matmul`` / ``build_chunked_
matmul_rs`` route there with ``path="pallas"`` and enforce that pin.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ddlb_tpu import faults, native, telemetry
from ddlb_tpu.primitives.base import accum_wire_dtypes


def fwd_perm(d: int):
    """The clockwise neighbor ring ``i -> i+1 (mod d)``."""
    return [(i, (i + 1) % d) for i in range(d)]


def plan_report(
    role: str, *, d: int, chunk_count: int, payload_elems: int,
    itemsize: int = 2,
) -> None:
    """Emit the planned chunk/ring-step schedule into the telemetry
    trace (host-side, at member construction): one ``overlap.chunk``
    span per chunk, one ``overlap.ring_step`` span per planned hop
    inside it — the structural record the trace reports join against
    when diagnosing a chunked member's schedule. Each planned hop is
    also a topology-fault injection site: a seeded degraded link
    charges its payload-proportional delay here on the affected rank
    (host-side — the traced ring itself cannot host a sleep), so the
    slow rank arrives late at the next collective exactly as a dragged
    ring schedule would. ``itemsize`` prices the hop payload (the sweep
    members ride bf16 wires by default)."""
    hops = max(0, d - 1)
    for j in range(chunk_count):
        with telemetry.span(
            "overlap.chunk", role=role, chunk=j, chunks=chunk_count,
            payload_elems=payload_elems,
        ):
            for t in range(hops):
                with telemetry.span("overlap.ring_step", chunk=j, step=t):
                    faults.inject(
                        "overlap.ring_step",
                        payload_bytes=payload_elems * itemsize,
                        role=role,
                    )


# ---------------------------------------------------------------------------
# per-chunk ring collectives (rotating ppermute buffers)
# ---------------------------------------------------------------------------


def ring_ag_chunk(piece, my_sched, *, axis_name: str, d: int):
    """Ring all-gather of one chunk: ``piece [r, ...]`` -> ``[d, r, ...]``
    rank-major. ``my_sched[t]`` is the rank whose piece this device
    holds after ``t`` forward hops (``(my - t) mod d``, the native
    planner's ``ag_fwd`` table row). The rotating buffer is the double
    buffer: the copy landing in ``out`` and the copy in flight."""
    fwd = fwd_perm(d)
    out = jnp.zeros((d,) + piece.shape, piece.dtype)
    buf = piece
    for t in range(d):
        out = jax.lax.dynamic_update_slice_in_dim(
            out, buf[None], my_sched[t], axis=0
        )
        if t + 1 < d:
            buf = jax.lax.ppermute(buf, axis_name, perm=fwd)
    return out


def ring_rs_chunk(partial, my_sched, *, axis_name: str, d: int,
                  block_rows: int, acc_t, wire_t):
    """Ring reduce-scatter of one chunk's partial sums:
    ``partial [d*block_rows, n]`` (local partials, rank-major blocks) ->
    ``[block_rows, n]`` — this device's block, summed over the ring.
    ``my_sched[t]`` is the block folded at step ``t`` (``(my + d - 1 -
    t) mod d``, the ``rs_fwd`` table row); the travelling accumulator
    rides the wire in ``wire_t`` and folds in ``acc_t`` (the MXU's
    native accumulation), same convention as the p2p rings."""
    fwd = fwd_perm(d)
    acc = jnp.zeros((block_rows, partial.shape[1]), acc_t)
    for t in range(d):
        block = jax.lax.dynamic_slice_in_dim(
            partial, my_sched[t] * block_rows, block_rows, axis=0
        )
        acc = acc + block.astype(acc_t)
        if t + 1 < d:
            acc = jax.lax.ppermute(
                acc.astype(wire_t), axis_name, perm=fwd
            ).astype(acc_t)
    return acc


def ring_a2a_chunk(x, *, axis_name: str, d: int):
    """All-to-all of one chunk as ``d-1`` shift-by-``t`` exchanges:
    ``x [d, g, ...]`` (block ``e`` bound for device ``e``) ->
    ``[d, g, ...]`` (block ``s`` arrived from device ``s``) — the
    ``lax.all_to_all(split_axis=0, concat_axis=0)`` contract. The
    diagonal block stays local, so the per-device wire is exactly
    ``(d-1)/d`` of the payload, the A2A closed form."""
    if d == 1:
        return x
    my = jax.lax.axis_index(axis_name)
    out = jnp.zeros_like(x)
    local = jax.lax.dynamic_slice_in_dim(x, my, 1, axis=0)
    out = jax.lax.dynamic_update_slice_in_dim(out, local, my, axis=0)
    for t in range(1, d):
        # device i sends its block for i+t directly to i+t; the payload
        # in flight and the block being consumed are the two live slots
        perm = [(i, (i + t) % d) for i in range(d)]
        send = jax.lax.dynamic_slice_in_dim(
            x, jax.lax.rem(my + t, d), 1, axis=0
        )
        recv = jax.lax.ppermute(send, axis_name, perm=perm)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, recv, jax.lax.rem(my - t + d, d), axis=0
        )
    return out


# ---------------------------------------------------------------------------
# family builders (shard_map bodies)
# ---------------------------------------------------------------------------


def build_chunked_ag_matmul(
    *,
    m: int,
    n: int,
    k: int,
    d: int,
    chunk_count: int,
    axis_name: str = "tp",
    path: str = "shard_map",
    interpret: Any = False,
):
    """AG+GEMM (tp_columnwise): ``a_shard [m/d, k]``, ``b [k, n]`` ->
    ``[m, n]``. Each device's shard is tiled into ``chunk_count``
    row-chunks; chunk ``j`` is ring-all-gathered and GEMMed while chunk
    ``j+1``'s ring flies. Requires ``m % (d * chunk_count) == 0``."""
    if m % (d * chunk_count):
        raise ValueError(
            f"m={m} must be divisible by partitions*chunk_count="
            f"{d * chunk_count} for the chunked engine"
        )
    if path == "pallas":
        return _pallas_ag_matmul(
            d=d, chunk_count=chunk_count, axis_name=axis_name,
            interpret=interpret,
        )
    rows_c = m // (d * chunk_count)  # rows per rank per chunk
    sched = jnp.asarray(native.ring_schedule(d, "ag_fwd"))
    plan_report("ag_matmul", d=d, chunk_count=chunk_count,
                payload_elems=rows_c * k)

    def step(a_shard, b):
        my = jax.lax.axis_index(axis_name)
        my_sched = sched[my]
        chunks = a_shard.reshape(chunk_count, rows_c, k)
        tiles = []
        for j in range(chunk_count):
            gathered = ring_ag_chunk(
                chunks[j], my_sched, axis_name=axis_name, d=d
            )  # [d, rows_c, k] rank-major
            tiles.append(gathered.reshape(d * rows_c, k) @ b)
        # tile j rows are rank-major; global order is rank-major then
        # chunk-major -> transpose (c, d) -> (d, c)
        out = jnp.stack(tiles)  # [c, d*rows_c, n]
        out = out.reshape(chunk_count, d, rows_c, n).transpose(1, 0, 2, 3)
        return out.reshape(m, n)

    return step


def build_chunked_matmul_rs(
    *,
    m: int,
    n: int,
    k: int,
    d: int,
    chunk_count: int,
    axis_name: str = "tp",
    path: str = "shard_map",
    interpret: Any = False,
):
    """GEMM+RS (tp_rowwise): ``a_shard [m, k/d]``, ``b_shard [k/d, n]``
    -> ``[m/d, n]`` (this device's fully-reduced rows). Chunk ``j``'s
    slab gathers the rows that land as every rank's local chunk-``j``
    block (the coll_pipeline reindex, done once at trace time); its
    partial GEMM then feeds a ring RS that flies under chunk ``j+1``'s
    GEMM. Requires ``m % (d * chunk_count) == 0``."""
    if m % (d * chunk_count):
        raise ValueError(
            f"m={m} must be divisible by partitions*chunk_count="
            f"{d * chunk_count} for the chunked engine"
        )
    if path == "pallas":
        return _pallas_matmul_rs(
            d=d, chunk_count=chunk_count, axis_name=axis_name,
            interpret=interpret,
        )
    rows_c = m // (d * chunk_count)  # rows per rank per chunk
    kd = k // d
    sched = jnp.asarray(native.ring_schedule(d, "rs_fwd"))
    plan_report("matmul_rs", d=d, chunk_count=chunk_count,
                payload_elems=rows_c * n)

    def step(a_shard, b_shard):
        my = jax.lax.axis_index(axis_name)
        my_sched = sched[my]
        # accumulate f32, ride the wire in the operand dtype (comm-volume
        # parity with the reference ring) — the single shared rule
        acc_t, wire_t = accum_wire_dtypes(a_shard.dtype)
        a4 = a_shard.reshape(d, chunk_count, rows_c, kd)
        outs = []
        for j in range(chunk_count):
            slab = a4[:, j].reshape(d * rows_c, kd)
            partial = jnp.matmul(slab, b_shard, preferred_element_type=acc_t)
            outs.append(
                ring_rs_chunk(
                    partial, my_sched, axis_name=axis_name, d=d,
                    block_rows=rows_c, acc_t=acc_t, wire_t=wire_t,
                )
            )  # [rows_c, n] — this rank's chunk-j rows, fully reduced
        # local row order is chunk-major: [c, rows_c, n] -> [m/d, n]
        return jnp.stack(outs).reshape(m // d, n).astype(a_shard.dtype)

    return step


def build_chunked_matmul_ar(
    *,
    m: int,
    n: int,
    k: int,
    d: int,
    chunk_count: int,
    axis_name: str = "tp",
):
    """GEMM+AR (dp_allreduce): ``a_shard [m, k/d]``, ``b_shard
    [k/d, n]`` -> ``[m, n]`` replicated. The gradient all-reduce is
    decomposed RS→AG around each chunk's grad GEMM: chunk ``j`` (a
    contiguous ``m/chunk_count`` row slab — every row is locally
    present in the k-sharded layout) GEMMs its partial, ring-reduce-
    scatters it, and ring-all-gathers the reduced blocks, with chunk
    ``j+1``'s GEMM overlapping both rings. Requires
    ``m % (d * chunk_count) == 0``."""
    if m % (d * chunk_count):
        raise ValueError(
            f"m={m} must be divisible by partitions*chunk_count="
            f"{d * chunk_count} for the chunked engine"
        )
    rows_c = m // (d * chunk_count)  # rows per rank-block per chunk
    kd = k // d
    sched_rs = jnp.asarray(native.ring_schedule(d, "rs_fwd"))
    sched_ag = jnp.asarray(native.ring_schedule(d, "ag_fwd"))
    plan_report("matmul_ar", d=d, chunk_count=chunk_count,
                payload_elems=rows_c * n)

    def step(a_shard, b_shard):
        my = jax.lax.axis_index(axis_name)
        my_rs, my_ag = sched_rs[my], sched_ag[my]
        # accumulate f32, ride the wire in the operand dtype — the
        # single shared rule (primitives.base.accum_wire_dtypes)
        acc_t, wire_t = accum_wire_dtypes(a_shard.dtype)
        a3 = a_shard.reshape(chunk_count, d * rows_c, kd)
        outs = []
        for j in range(chunk_count):
            partial = jnp.matmul(a3[j], b_shard, preferred_element_type=acc_t)
            red = ring_rs_chunk(
                partial, my_rs, axis_name=axis_name, d=d,
                block_rows=rows_c, acc_t=acc_t, wire_t=wire_t,
            )  # [rows_c, n] — this rank's block of the slab, reduced
            gathered = ring_ag_chunk(
                red.astype(a_shard.dtype), my_ag, axis_name=axis_name, d=d
            )  # [d, rows_c, n] rank-major == slab row order
            outs.append(gathered.reshape(d * rows_c, n))
        return jnp.concatenate(outs, axis=0)  # [m, n]

    return step


def build_chunked_alltoall_expert(
    *,
    m: int,
    n: int,
    k: int,
    d: int,
    chunk_count: int,
    axis_name: str = "tp",
):
    """Dispatch/GEMM/combine (ep_alltoall): ``a_loc [m/d, k]``,
    ``w_loc [1, k, n]`` (resident expert) -> ``[m/d, n]`` in token
    order. Every routing group is tiled into ``chunk_count`` chunks;
    chunk ``j``'s dispatch exchange, expert GEMM and combine exchange
    pipeline against chunks ``j±1``. Requires
    ``m % (d*d*chunk_count) == 0``."""
    if m % (d * d * chunk_count):
        raise ValueError(
            f"m={m} must be divisible by partitions^2*chunk_count="
            f"{d * d * chunk_count} for the chunked engine"
        )
    gc = m // (d * d * chunk_count)  # tokens per chunk per group
    plan_report("alltoall_expert", d=d, chunk_count=chunk_count,
                payload_elems=gc * k)

    def step(a_loc, w_loc):
        acc_t, _ = accum_wire_dtypes(a_loc.dtype)
        # [dst group, chunk, token, k]
        x = a_loc.reshape(d, chunk_count, gc, k)
        outs = []
        for j in range(chunk_count):
            xj = ring_a2a_chunk(x[:, j], axis_name=axis_name, d=d)
            yj = jnp.matmul(
                xj.reshape(d * gc, k), w_loc[0], preferred_element_type=acc_t
            )
            yj = yj.astype(a_loc.dtype).reshape(d, gc, n)
            outs.append(ring_a2a_chunk(yj, axis_name=axis_name, d=d))
        out = jnp.stack(outs, axis=1)  # [group, chunk, gc, n]
        return out.reshape(d * chunk_count * gc, n)

    return step


# ---------------------------------------------------------------------------
# pallas path (VMEM-resident double buffers; granularity pinned to the ring)
# ---------------------------------------------------------------------------


def _require_ring_granularity(chunk_count: int, d: int) -> None:
    if chunk_count != d:
        raise ValueError(
            f"the pallas path's semaphore protocol pins chunk_count to "
            f"the ring size (one chunk per RDMA step): got "
            f"chunk_count={chunk_count}, axis_size={d}"
        )


def _pallas_ag_matmul(*, d, chunk_count, axis_name, interpret):
    from ddlb_tpu.ops.collective_matmul import ring_ag_matmul

    _require_ring_granularity(chunk_count, d)

    def step(a_shard, b):
        return ring_ag_matmul(
            a_shard, b, axis_name=axis_name, axis_size=d,
            interpret=interpret,
        )

    return step


def _pallas_matmul_rs(*, d, chunk_count, axis_name, interpret):
    from ddlb_tpu.ops.collective_matmul import ring_matmul_rs

    _require_ring_granularity(chunk_count, d)

    def step(a_shard, b_shard):
        return ring_matmul_rs(
            a_shard, b_shard, axis_name=axis_name, axis_size=d,
            interpret=interpret,
        )

    return step
